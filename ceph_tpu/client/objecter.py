"""Objecter: client-side op lifecycle with epoch tracking and resend.

Analog of the reference's Objecter (reference: src/osdc/Objecter.cc —
``op_submit`` :2257, ``_calc_target`` re-running the OSDMap mapping chain
client-side :2786, ``_send_op`` :3239, and the resend-on-map-change scan
``_scan_requests``):

- the client holds ITS OWN OSDMap copy, which can be epochs behind the
  cluster's; every op's target (pg, primary, acting) is computed from that
  map and the op is stamped with the client's epoch;
- the OSD side (:meth:`~ceph_tpu.cluster.MiniCluster.osd_submit`) rejects
  ops that arrive with a stale epoch at a PG whose acting set has since
  changed, or that address an OSD that is no longer the primary — the
  reject carries the current map (the mon-subscription refresh the
  reference drives via ``CEPH_MSG_OSD_MAP``);
- on a reject, and proactively on :meth:`handle_osd_map`, the Objecter
  recomputes every in-flight op's target and RESENDS the ones whose
  target moved — so a write issued against a pre-remap map lands on the
  new acting set without the caller doing anything.
"""
from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field

from ..common.tracer import default_tracer
from ..osdmap import PG, OSDMap, ceph_stable_mod
from ..osdmap.str_hash import ceph_str_hash_rjenkins

MAX_ATTEMPTS = 8      # maps only move forward; a resend loop means a bug

_objecter_ids = itertools.count(1)

# live objecters (the live_daemons/live_engines pattern): the cluster's
# status() tick sweeps op timeouts on every objecter attached to it, so
# parked ops age into SLOW_OPS without anyone polling by hand
import weakref

_OBJECTERS: "weakref.WeakSet[Objecter]" = weakref.WeakSet()


def live_objecters() -> list["Objecter"]:
    return list(_OBJECTERS)


@dataclass
class _Op:
    """Objecter::Op (the in-flight bookkeeping, Objecter.h)."""
    tid: int
    pool_id: int
    oid: str
    data: bytes | None                    # None => read
    read_len: int = 0
    ops: list | None = None               # op VECTOR (IoCtx::operate path)
    snapid: int | None = None             # read AT this snap
    drain: bool = True                    # False = aio: queue, don't pump
    on_complete: object = None
    target: tuple | None = None           # (ps, primary, acting) last sent
    attempts: int = 0
    done: bool = False
    result: object = None
    # op-timeout accounting (ISSUE 9): parked ops (inactive PG, a shard
    # that never answers) older than osd_op_complaint_time get flagged
    # once by check_op_timeouts and counted on slow_ops -> SLOW_OPS
    submitted_at: float = 0.0
    slow: bool = False
    # the op's root TraceContext: every send/resend (and the whole
    # cross-daemon fan-out below it) stitches under ONE trace id
    trace: object = None


class Objecter:
    """Client op dispatcher over a MiniCluster's RADOS surface."""

    def __init__(self, cluster):
        self.cluster = cluster
        # the client's own map: starts current, goes stale as the cluster
        # moves on (unless wired to a monitor via handle_osd_map)
        self.osdmap: OSDMap = cluster.osdmap
        self.next_tid = 0
        self.inflight: dict[int, _Op] = {}
        self.resends = 0
        self.stale_rejects = 0
        # per-objecter perf collection: in-flight gauge + the slow_ops
        # counter the SLOW_OPS health check's window delta picks up (the
        # Objecter::op_timeout -> mon complaint path of the reference)
        from ..common.perf_counters import PerfCountersBuilder
        self.perf = (
            PerfCountersBuilder(f"objecter.{next(_objecter_ids)}")
            .add_u64("inflight", "client ops submitted and not yet "
                                 "completed (parked ops included)")
            .add_u64_counter("ops", "client ops submitted through this "
                                    "objecter")
            .add_u64_counter("slow_ops", "in-flight ops older than "
                                         "osd_op_complaint_time when "
                                         "check_op_timeouts ran")
            .create_perf_counters())
        cluster.cct.perf.add(self.perf)
        _OBJECTERS.add(self)

    def close(self) -> None:
        """Unhook the perf collection (a discarded objecter must not
        leave a frozen inflight gauge behind)."""
        self.cluster.cct.perf.remove(self.perf.name)
        _OBJECTERS.discard(self)

    def check_op_timeouts(self, now: float | None = None) -> list[int]:
        """Flag every in-flight op older than ``osd_op_complaint_time``
        (once per op) and count it on ``slow_ops`` — the client edge of
        SLOW_OPS: a black-holed or parked op becomes a health signal
        instead of a silent hang.  Returns the tids flagged."""
        now = time.monotonic() if now is None else now
        complaint = self.cluster.cct.conf.get("osd_op_complaint_time")
        flagged = []
        for op in list(self.inflight.values()):
            if not op.done and not op.slow and \
                    now - op.submitted_at >= complaint:
                op.slow = True
                self.perf.inc("slow_ops")
                flagged.append(op.tid)
        self.perf.set("inflight", len(self.inflight))
        return flagged

    # -- target computation (Objecter.cc:2786) -----------------------------

    def _calc_target(self, pool_id: int, oid: str) -> tuple[int, int, tuple]:
        pool = self.osdmap.pools[pool_id]
        ps = ceph_stable_mod(ceph_str_hash_rjenkins(oid), pool.pg_num,
                             pool.pg_num_mask)
        _, _, acting, _ = self.osdmap.pg_to_up_acting_osds(PG(pool_id, ps))
        primary = acting[0] if acting else -1
        return ps, primary, tuple(acting)

    # -- op lifecycle (Objecter.cc:2257 op_submit) -------------------------

    def write(self, pool_id: int, oid: str, data: bytes,
              on_complete=None) -> int:
        self.next_tid += 1
        op = _Op(self.next_tid, pool_id, oid, bytes(data),
                 on_complete=on_complete)
        self._track(op)
        self._send_op(op)
        return op.tid

    def _track(self, op: _Op) -> None:
        op.submitted_at = time.monotonic()
        self.inflight[op.tid] = op
        self.perf.inc("ops")
        self.perf.set("inflight", len(self.inflight))

    def operate(self, pool_id: int, oid: str, op,
                on_complete=None, snapid: int | None = None,
                drain: bool = True) -> int:
        """Submit a librados-style op VECTOR (ObjectOperation) through the
        full client lifecycle — epoch-stamped target, stale reject +
        resend on map change — landing in the primary's op engine
        (IoCtx::operate -> op_submit -> PrimaryLogPG::do_osd_ops).
        ``on_complete`` receives the MOSDOpReply."""
        self.next_tid += 1
        o = _Op(self.next_tid, pool_id, oid, None, ops=list(op.ops),
                snapid=snapid, drain=drain, on_complete=on_complete)
        self._track(o)
        self._send_op(o)
        return o.tid

    def read(self, pool_id: int, oid: str, length: int) -> bytes:
        """Synchronous read convenience (librados rados_read shape)."""
        self.next_tid += 1
        op = _Op(self.next_tid, pool_id, oid, None, read_len=length)
        self._track(op)
        self._send_op(op)
        if not op.done:
            self.inflight.pop(op.tid, None)    # no ghost resends later
            raise IOError(f"read of {oid} did not complete")
        if isinstance(op.result, Exception):
            raise op.result
        return op.result

    def _send_op(self, op: _Op) -> None:
        if op.attempts >= MAX_ATTEMPTS:
            op.done = True
            op.result = IOError(f"op {op.tid} exceeded {MAX_ATTEMPTS} sends")
            self.inflight.pop(op.tid, None)
            if op.on_complete:
                op.on_complete(op.result)
            return
        op.attempts += 1
        ps, primary, acting = self._calc_target(op.pool_id, op.oid)
        op.target = (ps, primary, acting)
        # the client edge of the distributed trace: one root context per
        # op (resends reuse it — they are the same logical op), activated
        # around the dispatch so the whole server-side fan-out chains
        # under the client.op span on the 'client' track
        tr = default_tracer()
        if op.trace is None:
            op.trace = tr.new_trace("client")
        # a RESEND is retry overhead by definition: its span is named
        # apart so the critical-path ledger charges the whole re-sent
        # attempt to the `retry` phase (the first attempt stays
        # client.op — the op itself, not its retries)
        span_name = "client.op" if op.attempts == 1 else "client.op_retry"
        with tr.activate(op.trace, track="client"), \
                tr.span(span_name, cat="client", oid=op.oid,
                        tid=op.tid, attempt=op.attempts):
            reply = self.cluster.osd_submit(
                op.pool_id, ps, primary, self.osdmap.epoch,
                oid=op.oid, data=op.data, read_len=op.read_len, ops=op.ops,
                snapid=op.snapid, drain=op.drain,
                on_done=lambda result, _op=op: self._op_done(_op, result))
        if reply is not None:             # ("stale", current_map)
            _, newer = reply
            self.stale_rejects += 1
            attempts_before = op.attempts
            self.handle_osd_map(newer)    # refresh + resend moved ops
            if (not op.done and op.tid in self.inflight and
                    op.attempts == attempts_before):
                # handle_osd_map did not resend us (target unchanged —
                # a pure epoch bump at the PG): resend explicitly
                self.resends += 1
                self._send_op(op)

    def _op_done(self, op: _Op, result) -> None:
        if op.done:
            return
        op.done = True
        op.result = result
        self.inflight.pop(op.tid, None)
        self.perf.set("inflight", len(self.inflight))
        if op.on_complete:
            op.on_complete(result)

    # -- map updates (the CEPH_MSG_OSD_MAP path + _scan_requests) ----------

    def handle_osd_map(self, new_map: OSDMap) -> None:
        """Adopt a newer map and resend every in-flight op whose target
        changed under it (Objecter.cc _scan_requests -> _send_op)."""
        if new_map.epoch <= self.osdmap.epoch:
            return
        self.osdmap = new_map
        for op in list(self.inflight.values()):
            if op.done:
                continue
            ps, primary, acting = self._calc_target(op.pool_id, op.oid)
            if (ps, primary, acting) != op.target:
                self.resends += 1
                self._send_op(op)

    def attach(self, mon) -> None:
        """Subscribe to a monitor's committed maps (mon session)."""
        mon.subscribers.append(lambda new_map, inc:
                               self.handle_osd_map(new_map))
