"""RadosStriper: large objects striped across RADOS objects.

Analog of the reference's libradosstriper (reference:
src/libradosstriper/RadosStriperImpl.cc — RAID-0 striping with
stripe_unit/stripe_count/object_size layout, piece objects named
"<soid>.%016x", and the layout+size stored as xattrs on the first
piece).  SURVEY §2.4 lists striping as one of the reference's
parallelism axes; here it is ALSO the TPU batching hook: a striped
write produces many whole RADOS objects at once, which EC pools encode
in ONE device dispatch via put_many's cross-PG coalescing
(ecutil.encode_many — the restructuring SURVEY §3.2 stars).

Layout semantics (Ceph file-layout striping): data advances in
stripe_unit chunks round-robin over a SET of stripe_count objects;
when every object of the set reaches object_size, the next set starts.
"""
from __future__ import annotations

from ..osd.osd_ops import ObjectOperation
from .rados import ObjectNotFound

LAYOUT_ATTR = "striper.layout"      # {su, sc, os, size} on piece 0


def piece_name(soid: str, idx: int) -> str:
    return f"{soid}.{idx:016x}"


class RadosStriper:
    def __init__(self, ioctx, stripe_unit: int = 65536,
                 stripe_count: int = 4, object_size: int = 1 << 20):
        if object_size % stripe_unit:
            raise ValueError("object_size must be a stripe_unit multiple")
        self.io = ioctx
        self.su = stripe_unit
        self.sc = stripe_count
        self.os = object_size

    # -- layout math --------------------------------------------------------

    def _piece_extents(self, length: int) -> list[tuple[int, list]]:
        """[(piece idx, [(piece off, logical off, n)])] covering length."""
        per_set = self.os * self.sc          # bytes per object set
        pieces: dict[int, list] = {}
        off = 0
        while off < length:
            set_no, set_off = divmod(off, per_set)
            row, row_off = divmod(set_off, self.su * self.sc)
            col, unit_off = divmod(row_off, self.su)
            idx = set_no * self.sc + col
            n = min(self.su - unit_off, length - off)
            pieces.setdefault(idx, []).append(
                (row * self.su + unit_off, off, n))
            off += n
        return sorted(pieces.items())

    # -- I/O -----------------------------------------------------------------

    def _layout_pieces(self, soid: str, lay: dict) -> set[str]:
        """Piece names implied by a recorded layout — the reference derives
        piece sets from the layout/size xattr (RadosStriperImpl.cc
        truncate/remove), never from a pool-wide name scan, because user
        objects may legitimately be named '<soid>.<16 hex>'.  A staged
        ``pending`` sub-layout (write_full's crash window between piece
        writes and the final xattr) contributes its piece set too, so an
        interrupted write can never orphan pieces."""
        pend = lay.get("pending") or []
        if isinstance(pend, dict):
            pend = [pend]
        names = {piece_name(soid, 0)}       # layout piece always exists
        for sub in (lay, *pend):
            if not sub:
                continue
            reader = RadosStriper(self.io, int(sub["su"]), int(sub["sc"]),
                                  int(sub["os"]))
            names |= {piece_name(soid, idx)
                      for idx, _ in reader._piece_extents(int(sub["size"]))}
        return names

    def write_full(self, soid: str, data: bytes) -> int:
        """Stripe ``data`` over piece objects; EC pools encode the whole
        batch in one device dispatch.  Returns the piece count.  A
        shrinking rewrite deletes the stale trailing pieces (the
        reference truncates/removes them on shrink) — the stale set is
        derived from the PREVIOUS layout xattr, so unrelated user objects
        whose names merely match the piece pattern are never touched."""
        data = bytes(data)
        old = self._load_layout(soid)        # None = no prior object
        pieces = self._piece_extents(len(data))
        bufs: dict[str, bytearray] = {}
        for idx, extents in pieces:
            buf = bufs.setdefault(piece_name(soid, idx), bytearray())
            for p_off, l_off, n in extents:
                if len(buf) < p_off + n:
                    buf.extend(b"\0" * (p_off + n - len(buf)))
                buf[p_off:p_off + n] = data[l_off:l_off + n]
        new_lay = {"su": self.su, "sc": self.sc, "os": self.os,
                   "size": len(data)}
        # STAGE the incoming layout before touching any other piece: if
        # the batched piece write (or this process) dies mid-way, the
        # layout on piece 0 still enumerates every piece either layout
        # could have produced, so the next write's sweep — and remove() —
        # reclaim the partial state instead of orphaning it
        staged = dict(old) if old is not None else dict(new_lay, size=0)
        prior_pend = staged.get("pending") or []
        if isinstance(prior_pend, dict):
            prior_pend = [prior_pend]
        # earlier interrupted writes keep their pending entries until THIS
        # write's commit point sweeps their pieces
        staged["pending"] = [new_lay, *prior_pend]
        p0 = piece_name(soid, 0)
        op0 = ObjectOperation()
        if p0 in bufs:
            # piece 0's data rides the SAME atomic vector as the staged
            # layout: the op engine keeps its object_info in sync (a
            # below-engine overwrite would leave a stale size on the
            # engine-created object and truncate reads to it)
            op0.write_full(bytes(bufs[p0]))
        op0.setxattr(LAYOUT_ATTR, staged)
        self.io.operate(p0, op0)
        cluster = self.io.rados.cluster
        # ONE batched device encode for all remaining pieces
        # (cross-PG coalescing)
        rest = {oid: bytes(b) for oid, b in bufs.items() if oid != p0}
        if rest:
            cluster.put_many(self.io.pool_id, rest)
        # switch the RECORDED layout to the new one BEFORE sweeping: the
        # base layout must never enumerate pieces the sweep has deleted
        # (a crash mid-sweep would otherwise leave reads dereferencing
        # removed trailing pieces).  The old layout — whose pieces the
        # sweep is about to reclaim — moves into pending until the sweep
        # finishes, so a crash mid-sweep stays reclaimable.
        old_pend = ([{f: old[f] for f in ("su", "sc", "os", "size")}]
                    if old is not None else []) + prior_pend
        mid = dict(new_lay)
        if old_pend:
            mid["pending"] = old_pend
            self.io.operate(p0, ObjectOperation().setxattr(
                LAYOUT_ATTR, mid))
        else:
            # fresh object, nothing to sweep: the staged write above is
            # superseded by this single clean commit
            self.io.operate(p0, ObjectOperation().setxattr(
                LAYOUT_ATTR, new_lay))
            return max(len(bufs), 1)
        # piece 0 always survives the sweep: an EMPTY object has no data
        # pieces but its layout piece holds the xattr
        stale = (self._layout_pieces(soid, staged) - set(bufs)
                 - {piece_name(soid, 0)})
        for oid in stale:
            try:
                self.io.remove_object(oid)
            except ObjectNotFound:
                pass                         # already gone — idempotent
        # the COMMIT point: sweep done, pending dropped
        self.io.operate(p0, ObjectOperation().setxattr(
            LAYOUT_ATTR, new_lay))
        return max(len(bufs), 1)

    def _layout(self, soid: str) -> dict:
        return self.io.get_xattr(piece_name(soid, 0), LAYOUT_ATTR)

    def _load_layout(self, soid: str) -> dict | None:
        """The recorded layout, or None when the striped object genuinely
        does not exist (no piece 0 / no layout attr).  Transient errors —
        a blocked PG, an I/O failure — PROPAGATE: treating them as
        'absent' would skip the shrink sweep and permanently orphan
        pieces that remove() (layout-derived) can no longer reach."""
        try:
            return self._layout(soid)
        except ObjectNotFound:
            return None
        except IOError as e:
            if getattr(e, "errno", None) == -61:    # ENODATA: no attr
                return None
            raise

    def stat(self, soid: str) -> int:
        return int(self._layout(soid)["size"])

    def read(self, soid: str, length: int | None = None,
             offset: int = 0) -> bytes:
        lay = self._layout(soid)
        su, sc, osz = int(lay["su"]), int(lay["sc"]), int(lay["os"])
        size = int(lay["size"])
        if length is None:
            length = size - offset
        end = min(offset + length, size)
        if end <= offset:
            return b""
        # reassemble with the WRITER's layout (it may differ from ours),
        # reading only the WINDOWED byte range of each piece — a small
        # read must not pull whole megabyte pieces through the decode
        reader = RadosStriper(self.io, su, sc, osz)
        out = bytearray(end - offset)
        for idx, extents in reader._piece_extents(size):
            wanted = []                   # (piece off, logical start, n)
            for p_off, l_off, n in extents:
                s = max(l_off, offset)
                e = min(l_off + n, end)
                if s < e:
                    wanted.append((p_off + (s - l_off), s, e - s))
            if not wanted:
                continue
            lo = min(w[0] for w in wanted)
            hi = max(w[0] + w[2] for w in wanted)
            data = self.io.read(piece_name(soid, idx), hi - lo, offset=lo)
            for p_off, s, n in wanted:
                out[s - offset:s - offset + n] = \
                    data[p_off - lo:p_off - lo + n].ljust(n, b"\0")
        return bytes(out)

    def remove(self, soid: str) -> int:
        """Delete every piece of the recorded layout (write_full's
        layout-derived shrink sweep guarantees no pieces outlive the
        layout, so the recorded set IS the complete set).  Piece 0 goes
        last: the layout must outlive the rest."""
        lay = self._load_layout(soid)
        if lay is None:
            raise ObjectNotFound(f"no striped object {soid!r}")
        pieces = sorted(self._layout_pieces(soid, lay), reverse=True)
        removed = 0
        for oid in pieces:
            try:
                self.io.remove_object(oid)
                removed += 1
            except ObjectNotFound:
                pass                         # sparse piece never written
        return removed
