"""RadosStriper: large objects striped across RADOS objects.

Analog of the reference's libradosstriper (reference:
src/libradosstriper/RadosStriperImpl.cc — RAID-0 striping with
stripe_unit/stripe_count/object_size layout, piece objects named
"<soid>.%016x", and the layout+size stored as xattrs on the first
piece).  SURVEY §2.4 lists striping as one of the reference's
parallelism axes; here it is ALSO the TPU batching hook: a striped
write produces many whole RADOS objects at once, which EC pools encode
in ONE device dispatch via put_many's cross-PG coalescing
(ecutil.encode_many — the restructuring SURVEY §3.2 stars).

Layout semantics (Ceph file-layout striping): data advances in
stripe_unit chunks round-robin over a SET of stripe_count objects;
when every object of the set reaches object_size, the next set starts.
"""
from __future__ import annotations

from ..osd.osd_ops import ObjectOperation
from .rados import ObjectNotFound

LAYOUT_ATTR = "striper.layout"      # {su, sc, os, size} on piece 0


def piece_name(soid: str, idx: int) -> str:
    return f"{soid}.{idx:016x}"


class RadosStriper:
    def __init__(self, ioctx, stripe_unit: int = 65536,
                 stripe_count: int = 4, object_size: int = 1 << 20):
        if object_size % stripe_unit:
            raise ValueError("object_size must be a stripe_unit multiple")
        self.io = ioctx
        self.su = stripe_unit
        self.sc = stripe_count
        self.os = object_size

    # -- layout math --------------------------------------------------------

    def _piece_extents(self, length: int) -> list[tuple[int, list]]:
        """[(piece idx, [(piece off, logical off, n)])] covering length."""
        per_set = self.os * self.sc          # bytes per object set
        pieces: dict[int, list] = {}
        off = 0
        while off < length:
            set_no, set_off = divmod(off, per_set)
            row, row_off = divmod(set_off, self.su * self.sc)
            col, unit_off = divmod(row_off, self.su)
            idx = set_no * self.sc + col
            n = min(self.su - unit_off, length - off)
            pieces.setdefault(idx, []).append(
                (row * self.su + unit_off, off, n))
            off += n
        return sorted(pieces.items())

    # -- I/O -----------------------------------------------------------------

    def _existing_pieces(self, soid: str) -> list[str]:
        """Piece objects of ``soid`` from the pool's listing — GROUND
        TRUTH, independent of any (possibly stale) layout attr."""
        prefix = f"{soid}."
        out = []
        for oid in self.io.list_objects():
            tail = oid[len(prefix):]
            if oid.startswith(prefix) and len(tail) == 16 and \
                    all(ch in "0123456789abcdef" for ch in tail):
                out.append(oid)
        return out

    def write_full(self, soid: str, data: bytes) -> int:
        """Stripe ``data`` over piece objects; EC pools encode the whole
        batch in one device dispatch.  Returns the piece count.  A
        shrinking rewrite deletes the stale trailing pieces (the
        reference truncates/removes them on shrink)."""
        data = bytes(data)
        pieces = self._piece_extents(len(data))
        bufs: dict[str, bytearray] = {}
        for idx, extents in pieces:
            buf = bufs.setdefault(piece_name(soid, idx), bytearray())
            for p_off, l_off, n in extents:
                if len(buf) < p_off + n:
                    buf.extend(b"\0" * (p_off + n - len(buf)))
                buf[p_off:p_off + n] = data[l_off:l_off + n]
        cluster = self.io.rados.cluster
        # ONE batched device encode for every piece (cross-PG coalescing)
        cluster.put_many(self.io.pool_id,
                         {oid: bytes(b) for oid, b in bufs.items()})
        self.io.operate(piece_name(soid, 0), ObjectOperation().setxattr(
            LAYOUT_ATTR, {"su": self.su, "sc": self.sc, "os": self.os,
                          "size": len(data)}))
        # piece 0 always survives the sweep: an EMPTY object has no data
        # pieces but its layout piece was just written above
        for stale in (set(self._existing_pieces(soid)) - set(bufs)
                      - {piece_name(soid, 0)}):
            self.io.remove_object(stale)
        return max(len(bufs), 1)

    def _layout(self, soid: str) -> dict:
        return self.io.get_xattr(piece_name(soid, 0), LAYOUT_ATTR)

    def stat(self, soid: str) -> int:
        return int(self._layout(soid)["size"])

    def read(self, soid: str, length: int | None = None,
             offset: int = 0) -> bytes:
        lay = self._layout(soid)
        su, sc, osz = int(lay["su"]), int(lay["sc"]), int(lay["os"])
        size = int(lay["size"])
        if length is None:
            length = size - offset
        end = min(offset + length, size)
        if end <= offset:
            return b""
        # reassemble with the WRITER's layout (it may differ from ours),
        # reading only the WINDOWED byte range of each piece — a small
        # read must not pull whole megabyte pieces through the decode
        reader = RadosStriper(self.io, su, sc, osz)
        out = bytearray(end - offset)
        for idx, extents in reader._piece_extents(size):
            wanted = []                   # (piece off, logical start, n)
            for p_off, l_off, n in extents:
                s = max(l_off, offset)
                e = min(l_off + n, end)
                if s < e:
                    wanted.append((p_off + (s - l_off), s, e - s))
            if not wanted:
                continue
            lo = min(w[0] for w in wanted)
            hi = max(w[0] + w[2] for w in wanted)
            data = self.io.read(piece_name(soid, idx), hi - lo, offset=lo)
            for p_off, s, n in wanted:
                out[s - offset:s - offset + n] = \
                    data[p_off - lo:p_off - lo + n].ljust(n, b"\0")
        return bytes(out)

    def remove(self, soid: str) -> int:
        """Delete every piece by pool-listing ground truth (layout-derived
        sets would orphan pieces left by an older, larger layout).
        Piece 0 goes last: the layout must outlive the rest."""
        pieces = sorted(self._existing_pieces(soid), reverse=True)
        if not pieces:
            raise ObjectNotFound(f"no striped object {soid!r}")
        for oid in pieces:
            self.io.remove_object(oid)
        return len(pieces)
