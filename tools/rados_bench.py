#!/usr/bin/env python3
"""rados_bench: open/closed-loop workload generator for the serving engine.

The serving-side sibling of ``rados bench`` (the cluster-level
write/seq bench lives at ``python -m ceph_tpu.bench.rados_bench``): this
tool drives CONCURRENT encode ops through ``ceph_tpu.exec.ServingEngine``
and reports throughput plus p50/p95/p99 latency — the numbers that decide
whether the op coalescer is earning its deadline.

    # closed loop, 64 clients, compare coalesced vs op-at-a-time:
    python tools/rados_bench.py --compare --concurrency 64 --ops 512

    # closed loop against one engine configuration:
    python tools/rados_bench.py --concurrency 64 --ops 1024 \
        --batch-max-ops 64 --op-size 16K --device jax

    # open loop at a fixed arrival rate (tail latency without
    # coordinated omission):
    python tools/rados_bench.py --mode open --rate 2000 --seconds 5

    # machine-readable:
    python tools/rados_bench.py --compare --json

``--unbatched`` pins ``batch_max_ops=1`` (every op is its own device
dispatch) — the baseline the coalesced number is judged against, on the
same device.
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def build_codec(args):
    from ceph_tpu.backend import StripeInfo
    from ceph_tpu.common import parse_size
    from ceph_tpu.plugins.registry import ErasureCodePluginRegistry
    profile = {"plugin": args.plugin, "k": str(args.k), "m": str(args.m),
               "technique": args.technique}
    if args.plugin == "jax_rs":
        profile["device"] = args.device
    ec = ErasureCodePluginRegistry.instance().factory(
        args.plugin, "", profile)
    return ec, StripeInfo(args.k, parse_size(args.chunk_size))


def human(result: dict, out) -> None:
    w = out.write
    if "batched" in result:
        for label in ("unbatched", "batched"):
            r = result[label]
            w(f"{label:>10}: {r['ops_s']:>9.1f} ops/s  "
              f"{r['mb_s']:>8.2f} MB/s  p50 {r['p50_ms']:.3f} ms  "
              f"p95 {r['p95_ms']:.3f} ms  p99 {r['p99_ms']:.3f} ms  "
              f"(mean batch {r['mean_batch_size']})\n")
        w(f"{'speedup':>10}: {result['speedup']}x coalesced vs "
          f"op-at-a-time\n")
        return
    w(f"Mode:               {result['mode']}\n")
    w(f"Ops completed:      {result['ops']}\n")
    if "rejected" in result:
        w(f"Ops rejected:       {result['rejected']}\n")
    w(f"Op size:            {result['op_bytes']}\n")
    w(f"Total time (s):     {result['elapsed_s']}\n")
    w(f"Throughput (ops/s): {result['ops_s']}\n")
    w(f"Bandwidth (MB/s):   {result['mb_s']}\n")
    w(f"Latency p50 (ms):   {result['p50_ms']}\n")
    w(f"Latency p95 (ms):   {result['p95_ms']}\n")
    w(f"Latency p99 (ms):   {result['p99_ms']}\n")
    w(f"Mean batch size:    {result['mean_batch_size']}\n")


def _pct(sorted_vals, p):
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(p / 100.0 * len(sorted_vals)))
    return sorted_vals[i]


def _closed_loop_segment(mux, n_clients: int, ops_per_client: int,
                         payload: bytes, timeout_s: float) -> dict:
    """One closed-loop burst over an ALREADY-CONNECTED mux: every logical
    session runs ``ops_per_client`` ping RPCs (next op submits when the
    previous completes; EBUSY sheds retry the same op).  Shared by
    :func:`run_mux_bench` (one segment per process) and
    :func:`run_mux_overhead_bench` (many segments against one warmed
    server, so segment-to-segment deltas isolate instrument cost from
    setup noise)."""
    import errno as _errno
    import threading
    import time

    total = n_clients * ops_per_client
    lock = threading.Lock()
    state = {"done": 0, "failed": 0, "shed_retries": 0}
    lats: list[float] = []
    finished = threading.Event()

    def mk_cb(sess, left):
        def cb(call):
            r = call.result
            shed = (not isinstance(r, BaseException)
                    and not r.ok and r.errno == _errno.EBUSY)
            with lock:
                if shed:
                    state["shed_retries"] += 1
                elif isinstance(r, BaseException) or not r.ok:
                    state["failed"] += 1
                    state["done"] += 1
                else:
                    lats.append(time.monotonic() - call.t_submit)
                    state["done"] += 1
                fin = state["done"] >= total
            if fin:
                finished.set()
                return
            if shed:        # refused: retry the SAME op
                sess.call_async("ping", {"payload": payload},
                                cb=mk_cb(sess, left))
            elif left > 1:  # completed: next op in the loop
                sess.call_async("ping", {"payload": payload},
                                cb=mk_cb(sess, left - 1))
        return cb

    t0 = time.perf_counter()
    for _ in range(n_clients):
        s = mux.session()
        s.call_async("ping", {"payload": payload}, cb=mk_cb(s, ops_per_client))
    ok = finished.wait(timeout_s)
    elapsed = time.perf_counter() - t0
    lats.sort()
    return {"finished_in_time": bool(ok), "elapsed_s": elapsed,
            "state": state, "lats": lats}


def run_mux_bench(n_clients: int = 10000, ops_per_client: int = 2,
                  n_conns: int = 8, payload_bytes: int = 64,
                  queue_max: int | None = None,
                  op_threads: int | None = None,
                  timeout_s: float = 120.0) -> dict:
    """Closed-loop mux bench: ``n_clients`` logical sessions multiplexed
    over ``n_conns`` TCP connections to an async ClusterServer, each
    running ``ops_per_client`` ping RPCs closed-loop (next op submits
    when the previous completes).  A shed (EBUSY) refusal RETRIES the op
    — goodput counts only completed work — so with ``queue_max`` set low
    this measures goodput + shed-rate UNDER OVERLOAD, and with it high
    it measures clean concurrency capacity.  Returns goodput (ops/s),
    latency percentiles, shed-rate, and transport stats.
    """
    import os
    import tempfile
    import threading
    import time

    from ceph_tpu.cluster import MiniCluster
    from ceph_tpu.msg import MuxClient
    from ceph_tpu.net import KEYRING, ClusterServer

    with tempfile.TemporaryDirectory() as td:
        cluster = MiniCluster(n_osds=3, osds_per_host=3, chunk_size=512,
                              data_dir=td)
        conf = cluster.cct.conf
        saved = {}
        overrides = {}
        if queue_max is not None:
            overrides["ms_async_dispatch_queue_max"] = queue_max
        if op_threads is not None:
            overrides["ms_async_op_threads"] = op_threads
        for k, v in overrides.items():
            saved[k] = conf.get(k)
            conf.set(k, v)
        server = ClusterServer(cluster)
        mux = None
        try:
            server.start()
            mux = MuxClient("127.0.0.1", server.port,
                            os.path.join(td, KEYRING), n_conns=n_conns)
            mux.connect()
            payload = b"\xab" * payload_bytes
            seg = _closed_loop_segment(mux, n_clients, ops_per_client,
                                       payload, timeout_s)
            ok = seg["finished_in_time"]
            elapsed = seg["elapsed_s"]
            state = seg["state"]
            lats = seg["lats"]
            st = mux.stats()
            shed_snap = (server._transport.shed.snapshot()
                         if server._transport is not None else {})
            completed = state["done"] - state["failed"]
            arrivals = completed + state["shed_retries"]
            return {
                "mode": "mux",
                "clients": n_clients,
                "connections": st["connections"],
                "ops_per_client": ops_per_client,
                "completed": completed,
                "failed": state["failed"],
                "finished_in_time": bool(ok),
                "elapsed_s": round(elapsed, 4),
                "ops_s": round(completed / elapsed, 1) if elapsed else 0.0,
                "p50_ms": round(_pct(lats, 50) * 1e3, 3),
                "p95_ms": round(_pct(lats, 95) * 1e3, 3),
                "p99_ms": round(_pct(lats, 99) * 1e3, 3),
                "shed_retries": state["shed_retries"],
                "shed_rate": round(
                    state["shed_retries"] / arrivals, 4) if arrivals
                else 0.0,
                "server_shed": shed_snap,
                "mux_stats": st,
                "threads": threading.active_count(),
            }
        finally:
            if mux is not None:
                mux.close()
            server.stop()
            cluster.shutdown()
            for k, v in saved.items():
                conf.set(k, v)


def run_mux_overhead_bench(n_clients: int = 64, ops_per_client: int = 300,
                           n_conns: int = 2, payload_bytes: int = 64,
                           rounds: int = 7, timeout_s: float = 120.0) -> dict:
    """Instrument-overhead A/B on the serving.async mux workload.

    One server and one warmed mux; ``rounds`` PAIRED closed-loop
    segments (instruments on vs off via the kill-switch) alternate over
    the SAME connections, each measured in PROCESS CPU time per op.
    Wall-clock throughput on a small shared host swings 2x run-to-run
    from scheduler noise and per-process setup differences; CPU-per-op
    against one warmed server isolates the work the instruments actually
    add.  The published overhead is the MEDIAN of the per-round paired
    deltas, with the on/off order alternating each round so slow drift
    cancels instead of biasing one arm.
    """
    import gc
    import os
    import tempfile
    import time

    from ceph_tpu.cluster import MiniCluster
    from ceph_tpu.common import instruments
    from ceph_tpu.msg import MuxClient
    from ceph_tpu.net import KEYRING, ClusterServer

    total = n_clients * ops_per_client
    with tempfile.TemporaryDirectory() as td:
        cluster = MiniCluster(n_osds=3, osds_per_host=3, chunk_size=512,
                              data_dir=td)
        server = ClusterServer(cluster)
        mux = None
        try:
            server.start()
            mux = MuxClient("127.0.0.1", server.port,
                            os.path.join(td, KEYRING), n_conns=n_conns)
            mux.connect()
            payload = b"\xab" * payload_bytes

            def segment(off: bool) -> dict:
                gc.collect()
                c0 = time.process_time()
                if off:
                    with instruments.disabled():
                        seg = _closed_loop_segment(
                            mux, n_clients, ops_per_client, payload,
                            timeout_s)
                else:
                    seg = _closed_loop_segment(
                        mux, n_clients, ops_per_client, payload, timeout_s)
                cpu = time.process_time() - c0
                state, lats = seg["state"], seg["lats"]
                completed = state["done"] - state["failed"]
                return {
                    "cpu_us_per_op": cpu / total * 1e6,
                    "ops_s": round(completed / seg["elapsed_s"], 1)
                    if seg["elapsed_s"] else 0.0,
                    "p99_ms": round(_pct(lats, 99) * 1e3, 3),
                    "completed": completed,
                }

            def median(vals):
                s = sorted(vals)
                m = len(s) // 2
                return s[m] if len(s) % 2 else (s[m - 1] + s[m]) / 2

            segment(False)    # warmup: discarded (cold code paths, sockets)
            deltas = []
            on_segs, off_segs = [], []
            for i in range(rounds):
                first_off = bool(i % 2)        # alternate A/B, B/A order
                a = segment(first_off)
                b = segment(not first_off)
                on_seg, off_seg = (b, a) if first_off else (a, b)
                on_segs.append(on_seg)
                off_segs.append(off_seg)
                deltas.append(
                    (on_seg["cpu_us_per_op"] - off_seg["cpu_us_per_op"])
                    / off_seg["cpu_us_per_op"] * 100.0)

            def arm(segs):
                return {
                    "ops_s": median([s["ops_s"] for s in segs]),
                    "p99_ms": median([s["p99_ms"] for s in segs]),
                    "cpu_us_per_op": round(
                        median([s["cpu_us_per_op"] for s in segs]), 2),
                }

            return {
                "mode": "mux-overhead",
                "clients": n_clients,
                "ops_per_client": ops_per_client,
                "connections": n_conns,
                "rounds": rounds,
                "overhead_pct": round(max(0.0, median(deltas)), 2),
                "deltas_pct": [round(d, 2) for d in sorted(deltas)],
                "instruments_on": arm(on_segs),
                "instruments_off": arm(off_segs),
            }
        finally:
            if mux is not None:
                mux.close()
            server.stop()
            cluster.shutdown()


def run_mux_overload_pair(n_clients: int = 10000,
                          ops_per_client: int = 2,
                          n_conns: int = 8,
                          overload_queue_max: int = 64) -> dict:
    """The bench.py ``serving.async`` block: one clean-capacity run
    (queue limit ABOVE the client count: nothing sheds) and one
    overload run (tiny dispatch queue, one worker: the shed ladder must
    refuse work while goodput continues)."""
    capacity = run_mux_bench(n_clients, ops_per_client, n_conns,
                             queue_max=max(2 * n_clients, 2048))
    overload = run_mux_bench(min(n_clients, 2000), ops_per_client,
                             n_conns, queue_max=overload_queue_max,
                             op_threads=1)
    return {
        "clients": capacity["clients"],
        "ops_s": capacity["ops_s"],
        "p99_ms": capacity["p99_ms"],
        "p50_ms": capacity["p50_ms"],
        "threads": capacity["threads"],
        "capacity": capacity,
        "overload": {
            "clients": overload["clients"],
            "ops_s": overload["ops_s"],
            "p99_ms": overload["p99_ms"],
            "shed_rate": overload["shed_rate"],
            "shed_retries": overload["shed_retries"],
            "server_shed": overload["server_shed"],
            "completed": overload["completed"],
        },
    }


def main(argv=None) -> int:
    from ceph_tpu.utils.platform import honour_jax_platforms_env
    honour_jax_platforms_env()
    ap = argparse.ArgumentParser(
        prog="rados_bench", description=__doc__.splitlines()[0])
    ap.add_argument("--mode", choices=["closed", "open", "mux"],
                    default="closed")
    ap.add_argument("--ops", type=int, default=512,
                    help="closed loop: total ops to complete")
    ap.add_argument("--concurrency", type=int, default=64,
                    help="closed loop: logical clients in flight")
    ap.add_argument("--rate", type=float, default=1000.0,
                    help="open loop: offered arrival rate (ops/s)")
    ap.add_argument("--seconds", type=float, default=5.0,
                    help="open loop: arrival window")
    ap.add_argument("--op-size", default="4K")
    ap.add_argument("--chunk-size", default="1K")
    ap.add_argument("--k", type=int, default=4)
    ap.add_argument("--m", type=int, default=2)
    ap.add_argument("--plugin", default="jax_rs")
    ap.add_argument("--device", default="jax",
                    help="jax_rs device: jax|numpy|auto (jax measures the "
                         "real dispatch path the coalescer amortizes)")
    ap.add_argument("--technique", default="reed_sol_van")
    ap.add_argument("--batch-max-ops", type=int, default=None,
                    help="coalescer cap (default: osd_batch_max_ops)")
    ap.add_argument("--batch-max-delay-ms", type=float, default=None)
    ap.add_argument("--unbatched", action="store_true",
                    help="op-at-a-time baseline (batch_max_ops=1)")
    ap.add_argument("--compare", action="store_true",
                    help="run batched AND unbatched, report the speedup")
    ap.add_argument("--warmup", type=int, default=64,
                    help="warmup ops per engine (compiles size buckets)")
    ap.add_argument("--clients", type=int, default=10000,
                    help="mux mode: logical closed-loop sessions")
    ap.add_argument("--ops-per-client", type=int, default=2,
                    help="mux mode: RPCs each session completes")
    ap.add_argument("--conns", type=int, default=8,
                    help="mux mode: TCP connections carrying all sessions")
    ap.add_argument("--overload-queue-max", type=int, default=64,
                    help="mux mode: dispatch-queue limit for the overload "
                         "arm (tiny = heavy shedding)")
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)

    if args.mode == "mux":
        result = run_mux_overload_pair(
            n_clients=args.clients, ops_per_client=args.ops_per_client,
            n_conns=args.conns,
            overload_queue_max=args.overload_queue_max)
        if args.as_json:
            print(json.dumps(result))
        else:
            w = sys.stdout.write
            w(f"mux capacity:  {result['clients']} clients over "
              f"{args.conns} conns  {result['ops_s']:.0f} ops/s  "
              f"p50 {result['p50_ms']:.3f} ms  "
              f"p99 {result['p99_ms']:.3f} ms  "
              f"threads {result['threads']}\n")
            ov = result["overload"]
            w(f"mux overload:  {ov['clients']} clients  "
              f"{ov['ops_s']:.0f} ops/s goodput  "
              f"p99 {ov['p99_ms']:.3f} ms  "
              f"shed-rate {ov['shed_rate']:.2%} "
              f"({ov['shed_retries']} refusals)\n")
        return 0

    from ceph_tpu.common import parse_size
    from ceph_tpu.exec import ServingEngine
    from ceph_tpu.exec.workload import (closed_loop,
                                        compare_batched_unbatched,
                                        make_payloads, open_loop)
    ec, sinfo = build_codec(args)
    op_bytes = parse_size(args.op_size)
    print(f"# k={args.k} m={args.m} chunk={sinfo.chunk_size} "
          f"op={op_bytes} plugin={args.plugin} device={args.device}",
          file=sys.stderr)

    if args.compare:
        result = compare_batched_unbatched(
            ec, sinfo, n_ops=args.ops, concurrency=args.concurrency,
            op_bytes=op_bytes, warmup_ops=args.warmup,
            batch_max_ops=args.batch_max_ops)
    else:
        engine = ServingEngine(
            ec_impl=ec, sinfo=sinfo, name="rados_bench",
            max_ops=max(1024, args.concurrency * 2),
            max_bytes=max(64 << 20, args.concurrency * op_bytes * 4),
            batch_max_ops=1 if args.unbatched else args.batch_max_ops,
            batch_max_delay_ms=args.batch_max_delay_ms).start()
        try:
            payloads = make_payloads(op_bytes)
            if args.warmup:
                closed_loop(engine, args.warmup,
                            min(args.concurrency, args.warmup), payloads)
            if args.mode == "closed":
                result = closed_loop(engine, args.ops, args.concurrency,
                                     payloads)
            else:
                result = open_loop(engine, args.rate, args.seconds,
                                   payloads)
        finally:
            engine.stop()

    if args.as_json:
        print(json.dumps(result))
    else:
        human(result, sys.stdout)
    return 0


if __name__ == "__main__":
    sys.exit(main())
