#!/usr/bin/env python3
"""rados_bench: open/closed-loop workload generator for the serving engine.

The serving-side sibling of ``rados bench`` (the cluster-level
write/seq bench lives at ``python -m ceph_tpu.bench.rados_bench``): this
tool drives CONCURRENT encode ops through ``ceph_tpu.exec.ServingEngine``
and reports throughput plus p50/p95/p99 latency — the numbers that decide
whether the op coalescer is earning its deadline.

    # closed loop, 64 clients, compare coalesced vs op-at-a-time:
    python tools/rados_bench.py --compare --concurrency 64 --ops 512

    # closed loop against one engine configuration:
    python tools/rados_bench.py --concurrency 64 --ops 1024 \
        --batch-max-ops 64 --op-size 16K --device jax

    # open loop at a fixed arrival rate (tail latency without
    # coordinated omission):
    python tools/rados_bench.py --mode open --rate 2000 --seconds 5

    # machine-readable:
    python tools/rados_bench.py --compare --json

``--unbatched`` pins ``batch_max_ops=1`` (every op is its own device
dispatch) — the baseline the coalesced number is judged against, on the
same device.
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def build_codec(args):
    from ceph_tpu.backend import StripeInfo
    from ceph_tpu.common import parse_size
    from ceph_tpu.plugins.registry import ErasureCodePluginRegistry
    profile = {"plugin": args.plugin, "k": str(args.k), "m": str(args.m),
               "technique": args.technique}
    if args.plugin == "jax_rs":
        profile["device"] = args.device
    ec = ErasureCodePluginRegistry.instance().factory(
        args.plugin, "", profile)
    return ec, StripeInfo(args.k, parse_size(args.chunk_size))


def human(result: dict, out) -> None:
    w = out.write
    if "batched" in result:
        for label in ("unbatched", "batched"):
            r = result[label]
            w(f"{label:>10}: {r['ops_s']:>9.1f} ops/s  "
              f"{r['mb_s']:>8.2f} MB/s  p50 {r['p50_ms']:.3f} ms  "
              f"p95 {r['p95_ms']:.3f} ms  p99 {r['p99_ms']:.3f} ms  "
              f"(mean batch {r['mean_batch_size']})\n")
        w(f"{'speedup':>10}: {result['speedup']}x coalesced vs "
          f"op-at-a-time\n")
        return
    w(f"Mode:               {result['mode']}\n")
    w(f"Ops completed:      {result['ops']}\n")
    if "rejected" in result:
        w(f"Ops rejected:       {result['rejected']}\n")
    w(f"Op size:            {result['op_bytes']}\n")
    w(f"Total time (s):     {result['elapsed_s']}\n")
    w(f"Throughput (ops/s): {result['ops_s']}\n")
    w(f"Bandwidth (MB/s):   {result['mb_s']}\n")
    w(f"Latency p50 (ms):   {result['p50_ms']}\n")
    w(f"Latency p95 (ms):   {result['p95_ms']}\n")
    w(f"Latency p99 (ms):   {result['p99_ms']}\n")
    w(f"Mean batch size:    {result['mean_batch_size']}\n")


def _pct(sorted_vals, p):
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(p / 100.0 * len(sorted_vals)))
    return sorted_vals[i]


class WorkloadKeys:
    """Deterministic key streams for production-shaped workloads: a
    uniform or zipfian draw over an ``n_keys`` keyspace, optionally
    overlaid with a FLASH CROWD — a window of the run during which a
    fraction of arrivals collapses onto a tiny hot set (the head of the
    zipf ranking), the millions-of-users "everyone opens the same
    object" shape a cache tier exists for.

    Coordinates are op-sequence PROGRESS (0..1), not wall-clock, so a
    stream is reproducible at any scale: generating 10k clients' keys
    is 10k * ops calls of :meth:`key`, seeded once.  Thread-safe (mux
    completion callbacks submit from reactor threads)."""

    def __init__(self, n_keys: int = 10000, dist: str = "uniform",
                 zipf_s: float = 1.1, flash: tuple | None = None,
                 hot_frac: float = 0.001, seed: int = 0,
                 prefix: str = "obj"):
        import random
        import threading
        if dist not in ("uniform", "zipf"):
            raise ValueError(f"unknown key distribution {dist!r}")
        if flash is not None:
            frac, start, dur = flash
            if not (0.0 <= frac <= 1.0 and 0.0 <= start <= 1.0
                    and 0.0 <= dur <= 1.0):
                raise ValueError(f"flash-crowd out of [0,1]: {flash}")
        self.n = int(n_keys)
        self.dist = dist
        self.s = float(zipf_s)
        self.flash = flash
        self.hot = max(1, int(round(hot_frac * self.n)))
        self.prefix = prefix
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._seen: set[int] = set()
        self.counts = {"total": 0, "flash": 0}
        if dist == "zipf":
            # rank r (1-based) with P(r) proportional to 1/r^s: an
            # explicit CDF + bisect — exact, no rejection loop, and the
            # head of the ranking doubles as the flash-crowd hot set
            acc, cdf = 0.0, []
            for r in range(1, self.n + 1):
                acc += 1.0 / (r ** self.s)
                cdf.append(acc)
            self._cdf = [c / acc for c in cdf]

    def _rank(self) -> int:
        if self.dist == "zipf":
            import bisect
            return bisect.bisect_left(self._cdf, self._rng.random())
        return self._rng.randrange(self.n)

    def key(self, progress: float) -> str:
        """The next key for an arrival at ``progress`` (0..1) of the
        run: hot-set draw inside the flash-crowd window, the base
        distribution outside it."""
        with self._lock:
            self.counts["total"] += 1
            rank = None
            if self.flash is not None:
                frac, start, dur = self.flash
                if start <= progress < start + dur \
                        and self._rng.random() < frac:
                    self.counts["flash"] += 1
                    rank = self._rng.randrange(self.hot)
            if rank is None:
                rank = self._rank()
            self._seen.add(rank)
            return f"{self.prefix}{rank:08d}"

    def describe(self) -> dict:
        with self._lock:
            return {"dist": self.dist,
                    "zipf_s": self.s if self.dist == "zipf" else None,
                    "n_keys": self.n,
                    "hot_set": self.hot,
                    "flash": list(self.flash) if self.flash else None,
                    "keys_drawn": self.counts["total"],
                    "flash_draws": self.counts["flash"],
                    "distinct_keys": len(self._seen)}


def parse_key_dist(spec: str) -> tuple[str, float]:
    """``uniform`` or ``zipf:<s>`` -> (dist, s)."""
    if spec == "uniform":
        return "uniform", 0.0
    if spec.startswith("zipf:"):
        return "zipf", float(spec.split(":", 1)[1])
    if spec == "zipf":
        return "zipf", 1.1
    raise ValueError(f"--key-dist {spec!r}: expected uniform or zipf:<s>")


def parse_flash_crowd(spec: str) -> tuple[float, float, float]:
    """``frac:start:dur`` (all 0..1, progress coordinates) -> tuple."""
    parts = spec.split(":")
    if len(parts) != 3:
        raise ValueError(
            f"--flash-crowd {spec!r}: expected frac:start:dur")
    return float(parts[0]), float(parts[1]), float(parts[2])


def _closed_loop_segment(mux, n_clients: int, ops_per_client: int,
                         payload: bytes, timeout_s: float,
                         keys: WorkloadKeys | None = None,
                         method: str = "ping",
                         extra: dict | None = None) -> dict:
    """One closed-loop burst over an ALREADY-CONNECTED mux: every logical
    session runs ``ops_per_client`` RPCs (next op submits when the
    previous completes; EBUSY sheds retry the same op).  ``method``
    picks the op — ``ping`` (transport echo), ``tier_read`` (served
    through the cluster, ``extra`` carrying the pool), or a CALLABLE
    ``progress -> (method, args)`` for mixed streams (the tiering
    bench's read/write flash crowd).  Shared by
    :func:`run_mux_bench` (one segment per process),
    :func:`run_mux_overhead_bench` (many segments against one warmed
    server, so segment-to-segment deltas isolate instrument cost from
    setup noise) and :func:`run_tier_mux_bench` (cold/warm tier arms
    against one preloaded cluster)."""
    import errno as _errno
    import threading
    import time

    total = n_clients * ops_per_client
    lock = threading.Lock()
    state = {"done": 0, "failed": 0, "shed_retries": 0}
    lats: list[float] = []
    finished = threading.Event()

    def _op():
        # a fresh arrival draws its method + key at the CURRENT
        # progress of the run, so the flash-crowd window covers a
        # contiguous slice of the op sequence at any client count
        with lock:
            progress = state["done"] / total
        if callable(method):
            m, a = method(progress)
        else:
            m = method
            a = {"payload": payload} if m == "ping" else dict(extra or {})
        if keys is not None:
            a["key"] = keys.key(progress)
        return m, a

    def mk_cb(sess, left, m, args):
        def cb(call):
            r = call.result
            shed = (not isinstance(r, BaseException)
                    and not r.ok and r.errno == _errno.EBUSY)
            with lock:
                if shed:
                    state["shed_retries"] += 1
                elif isinstance(r, BaseException) or not r.ok:
                    state["failed"] += 1
                    state["done"] += 1
                else:
                    lats.append(time.monotonic() - call.t_submit)
                    state["done"] += 1
                fin = state["done"] >= total
            if fin:
                finished.set()
                return
            if shed:        # refused: retry the SAME op (same key)
                sess.call_async(m, args, cb=mk_cb(sess, left, m, args))
            elif left > 1:  # completed: next op in the loop
                nm, na = _op()
                sess.call_async(nm, na, cb=mk_cb(sess, left - 1, nm, na))
        return cb

    t0 = time.perf_counter()
    for _ in range(n_clients):
        s = mux.session()
        m0, first = _op()
        s.call_async(m0, first, cb=mk_cb(s, ops_per_client, m0, first))
    ok = finished.wait(timeout_s)
    elapsed = time.perf_counter() - t0
    lats.sort()
    return {"finished_in_time": bool(ok), "elapsed_s": elapsed,
            "state": state, "lats": lats}


def run_mux_bench(n_clients: int = 10000, ops_per_client: int = 2,
                  n_conns: int = 8, payload_bytes: int = 64,
                  queue_max: int | None = None,
                  op_threads: int | None = None,
                  timeout_s: float = 120.0,
                  keys: WorkloadKeys | None = None,
                  conf_overrides: dict | None = None,
                  distinct_payloads: bool = False) -> dict:
    """Closed-loop mux bench: ``n_clients`` logical sessions multiplexed
    over ``n_conns`` TCP connections to an async ClusterServer, each
    running ``ops_per_client`` ping RPCs closed-loop (next op submits
    when the previous completes).  A shed (EBUSY) refusal RETRIES the op
    — goodput counts only completed work — so with ``queue_max`` set low
    this measures goodput + shed-rate UNDER OVERLOAD, and with it high
    it measures clean concurrency capacity.  Returns goodput (ops/s),
    latency percentiles, shed-rate, and transport stats.
    """
    import os
    import tempfile
    import threading
    import time

    from ceph_tpu.cluster import MiniCluster
    from ceph_tpu.msg import MuxClient
    from ceph_tpu.net import KEYRING, ClusterServer

    with tempfile.TemporaryDirectory() as td:
        cluster = MiniCluster(n_osds=3, osds_per_host=3, chunk_size=512,
                              data_dir=td)
        conf = cluster.cct.conf
        saved = {}
        overrides = {}
        if queue_max is not None:
            overrides["ms_async_dispatch_queue_max"] = queue_max
        if op_threads is not None:
            overrides["ms_async_op_threads"] = op_threads
        # extra conf keys (e.g. ms_zero_copy arms) ride the same
        # save/restore cycle; the cluster cct IS the process default
        # context, so the mux client's config observers see them too
        overrides.update(conf_overrides or {})
        for k, v in overrides.items():
            saved[k] = conf.get(k)
            conf.set(k, v)
        server = ClusterServer(cluster)
        mux = None
        try:
            server.start()
            mux = MuxClient("127.0.0.1", server.port,
                            os.path.join(td, KEYRING), n_conns=n_conns)
            mux.connect()
            payload = b"\xab" * payload_bytes
            # distinct_payloads: a FRESH bytes object per op.  The
            # default shares ONE payload object across every call in a
            # batch, which pickle memoizes — the legacy frame then
            # carries the payload once however many calls ride it, a
            # wire-volume fiction no real workload gets.  Copy-path
            # arms (run_zero_copy_pair) need each op to weigh its own
            # bytes on both serialize paths.
            # bytes(payload) would return the SAME object — go through
            # bytearray to force a genuinely fresh one
            meth = (lambda _p: ("ping",
                                {"payload": bytes(bytearray(payload))})) \
                if distinct_payloads else "ping"
            seg = _closed_loop_segment(mux, n_clients, ops_per_client,
                                       payload, timeout_s, keys=keys,
                                       method=meth)
            ok = seg["finished_in_time"]
            elapsed = seg["elapsed_s"]
            state = seg["state"]
            lats = seg["lats"]
            st = mux.stats()
            shed_snap = (server._transport.shed.snapshot()
                         if server._transport is not None else {})
            completed = state["done"] - state["failed"]
            arrivals = completed + state["shed_retries"]
            return {
                "mode": "mux",
                "clients": n_clients,
                "connections": st["connections"],
                "ops_per_client": ops_per_client,
                "completed": completed,
                "failed": state["failed"],
                "finished_in_time": bool(ok),
                "elapsed_s": round(elapsed, 4),
                "ops_s": round(completed / elapsed, 1) if elapsed else 0.0,
                "p50_ms": round(_pct(lats, 50) * 1e3, 3),
                "p95_ms": round(_pct(lats, 95) * 1e3, 3),
                "p99_ms": round(_pct(lats, 99) * 1e3, 3),
                "shed_retries": state["shed_retries"],
                "shed_rate": round(
                    state["shed_retries"] / arrivals, 4) if arrivals
                else 0.0,
                "server_shed": shed_snap,
                "mux_stats": st,
                "threads": threading.active_count(),
                "workload": keys.describe() if keys is not None else None,
            }
        finally:
            if mux is not None:
                mux.close()
            server.stop()
            cluster.shutdown()
            for k, v in saved.items():
                conf.set(k, v)


def run_tier_mux_bench(n_clients: int = 10000, ops_per_client: int = 2,
                       n_conns: int = 8, n_objects: int = 1000,
                       object_bytes: int = 2048, zipf_s: float = 1.1,
                       flash: tuple = (0.9, 0.0, 1.0),
                       hot_frac: float = 0.001, write_frac: float = 0.2,
                       seed: int = 17, device: str = "numpy",
                       timeout_s: float = 300.0) -> dict:
    """Flash-crowd tiering bench at mux scale: ``n_clients`` logical
    sessions run a zipf + flash-crowd key stream (``hot_frac`` of the
    keyspace — 0.1% by default — absorbing ``flash[0]`` of arrivals)
    of closed-loop mixed tier_read/tier_write RPCs (``write_frac``
    writes) against one preloaded cluster, three segments with
    IDENTICAL streams (same seed):

    - **cold**: no tier bound — reads are full EC base-pool reads over
      the wire (the path a miss proxies to) and writes are EC
      full-stripe writes, encode and all;
    - **warmup**: a writeback tier bound over the base — misses
      promote (min_recency 1), writes absorb, populating the hot set;
    - **warm**: the same stream against the warmed tier — the number
      the cache exists for.

    Device seconds per segment come from the critical-path ledger
    (DEVICE-phase attribution: codec dispatches and host-SIMD fallback
    both land there).  A healthy EC READ never touches the codec, so
    the cold arm's device time is its write encodes — exactly the work
    writeback absorption elides — and warm-vs-cold compares
    device-time-per-op as well as p99.  Returns cold/warm p99 + device
    time, the warm pass's hit rate and promotion churn, and the
    workload description.
    """
    import os
    import random
    import tempfile
    import sys as _sys

    from ceph_tpu.cluster import MiniCluster
    from ceph_tpu.common import Context
    from ceph_tpu.common.tracer import default_tracer
    from ceph_tpu.msg import MuxClient
    from ceph_tpu.net import KEYRING, ClusterServer
    from ceph_tpu.osd.osd_ops import ObjectOperation

    def _mk_keys():
        # one stream per segment, SAME seed: the zipf ranks and flash
        # decisions replay draw-for-draw, so cold and warm arms serve
        # the same key sequence
        return WorkloadKeys(n_keys=n_objects, dist="zipf", zipf_s=zipf_s,
                            flash=flash, hot_frac=hot_frac, seed=seed)

    def _device_seconds(cluster) -> float:
        cluster.critpath.refresh()
        return sum(acc.get("device", 0.0)
                   for acc in cluster.critpath.phase_seconds().values())

    with tempfile.TemporaryDirectory() as td:
        cct = Context(overrides={
            # promote on the first recorded hit-set appearance: a flash
            # crowd earns residency immediately, like the reference's
            # min_read_recency_for_promote=1 deployments
            "tier_promote_min_recency": 1,
            "tier_target_max_objects": max(256, n_objects),
        })
        cluster = MiniCluster(n_osds=6, osds_per_host=2, chunk_size=512,
                              cct=cct, data_dir=td)
        server = None
        mux = None
        try:
            base = cluster.create_ec_pool(
                "tierbase", {"k": "2", "m": "1", "device": device},
                pg_num=4)
            cache = cluster.create_replicated_pool(
                "tiercache", size=3, pg_num=4,
                params={"hit_set_count": "4", "hit_set_period": "3600"})
            for i in range(n_objects):
                data = bytes([(i + j) % 251
                              for j in range(64)]) * (object_bytes // 64)
                cluster.operate(base, f"obj{i:08d}",
                                ObjectOperation().write_full(data))
            server = ClusterServer(cluster)
            server.start()
            mux = MuxClient("127.0.0.1", server.port,
                            os.path.join(td, KEYRING), n_conns=n_conns)
            mux.connect()

            wdata = bytes(range(64)) * (object_bytes // 64)

            def _mix(pool: str):
                # the read/write choice replays draw-for-draw across
                # segments (own seeded rng, consumed once per arrival)
                wrng = random.Random(seed ^ 0x5BD1)

                def draw(progress):
                    if wrng.random() < write_frac:
                        return "tier_write", {"pool": pool,
                                              "payload": wdata}
                    return "tier_read", {"pool": pool}
                return draw

            def _segment(pool: str, keys: WorkloadKeys) -> dict:
                d0 = _device_seconds(cluster)
                seg = _closed_loop_segment(
                    mux, n_clients, ops_per_client, b"", timeout_s,
                    keys=keys, method=_mix(pool))
                dd = _device_seconds(cluster) - d0
                st, lats = seg["state"], seg["lats"]
                done = st["done"] - st["failed"]
                return {"completed": done, "failed": st["failed"],
                        "finished_in_time": seg["finished_in_time"],
                        "elapsed_s": round(seg["elapsed_s"], 4),
                        "ops_s": round(done / seg["elapsed_s"], 1)
                        if seg["elapsed_s"] else 0.0,
                        "p50_ms": round(_pct(lats, 50) * 1e3, 3),
                        "p99_ms": round(_pct(lats, 99) * 1e3, 3),
                        "device_s": round(dd, 6),
                        "device_us_per_op": round(dd / done * 1e6, 3)
                        if done else 0.0}

            default_tracer().reset()
            cold = _segment("tierbase", _mk_keys())
            print(f"# tiering: cold p99 {cold['p99_ms']:.2f} ms, "
                  f"{cold['device_us_per_op']:.0f} us device/op",
                  file=_sys.stderr)

            svc = cluster.create_tier(cache, base)
            c0 = dict(svc.stats()["counters"])
            warmup = _segment("tiercache", _mk_keys())
            c1 = dict(svc.stats()["counters"])
            warm = _segment("tiercache", _mk_keys())
            c2 = dict(svc.stats()["counters"])

            def _delta(a, b, k):
                return int(b.get(k, 0)) - int(a.get(k, 0))

            hits = _delta(c1, c2, "hit")
            misses = _delta(c1, c2, "miss")
            warm["hit_rate"] = round(hits / (hits + misses), 4) \
                if hits + misses else 0.0
            warm["promotions"] = _delta(c1, c2, "promote")
            warmup_block = {"elapsed_s": warmup["elapsed_s"],
                            "promotions": _delta(c0, c1, "promote"),
                            "hit_rate": round(
                                _delta(c0, c1, "hit")
                                / max(1, _delta(c0, c1, "hit")
                                      + _delta(c0, c1, "miss")), 4)}
            keys_desc = _mk_keys()
            out = {
                "mode": "tier-mux",
                "device": device,
                "clients": n_clients,
                "ops_per_client": ops_per_client,
                "objects": n_objects,
                "object_bytes": object_bytes,
                "hot_objects": keys_desc.hot,
                "resident": len(svc.resident()),
                "cold": cold,
                "warmup": warmup_block,
                "warm": warm,
                "workload": {"dist": "zipf", "zipf_s": zipf_s,
                             "hot_frac": hot_frac, "flash": list(flash),
                             "write_frac": write_frac, "seed": seed},
            }
            if cold["p99_ms"]:
                out["warm_over_cold_p99"] = round(
                    warm["p99_ms"] / cold["p99_ms"], 4)
            if cold["device_us_per_op"]:
                out["warm_over_cold_device_us"] = round(
                    warm["device_us_per_op"] / cold["device_us_per_op"],
                    4)
            print(f"# tiering: warm p99 {warm['p99_ms']:.2f} ms, "
                  f"{warm['device_us_per_op']:.0f} us device/op, "
                  f"hit rate {warm['hit_rate']:.3f}, "
                  f"{warm['promotions']} promotions", file=_sys.stderr)
            return out
        finally:
            if mux is not None:
                mux.close()
            if server is not None:
                server.stop()
            cluster.shutdown()


def run_mux_overhead_bench(n_clients: int = 64, ops_per_client: int = 300,
                           n_conns: int = 2, payload_bytes: int = 64,
                           rounds: int = 7, timeout_s: float = 120.0) -> dict:
    """Instrument-overhead A/B on the serving.async mux workload.

    One server and one warmed mux; ``rounds`` PAIRED closed-loop
    segments (instruments on vs off via the kill-switch) alternate over
    the SAME connections, each measured in PROCESS CPU time per op.
    Wall-clock throughput on a small shared host swings 2x run-to-run
    from scheduler noise and per-process setup differences; CPU-per-op
    against one warmed server isolates the work the instruments actually
    add.  The published overhead is the MEDIAN of the per-round paired
    deltas, with the on/off order alternating each round so slow drift
    cancels instead of biasing one arm.
    """
    import gc
    import os
    import tempfile
    import time

    from ceph_tpu.cluster import MiniCluster
    from ceph_tpu.common import instruments
    from ceph_tpu.msg import MuxClient
    from ceph_tpu.net import KEYRING, ClusterServer

    total = n_clients * ops_per_client
    with tempfile.TemporaryDirectory() as td:
        cluster = MiniCluster(n_osds=3, osds_per_host=3, chunk_size=512,
                              data_dir=td)
        server = ClusterServer(cluster)
        mux = None
        try:
            server.start()
            mux = MuxClient("127.0.0.1", server.port,
                            os.path.join(td, KEYRING), n_conns=n_conns)
            mux.connect()
            payload = b"\xab" * payload_bytes

            def segment(off: bool) -> dict:
                gc.collect()
                c0 = time.process_time()
                if off:
                    with instruments.disabled():
                        seg = _closed_loop_segment(
                            mux, n_clients, ops_per_client, payload,
                            timeout_s)
                else:
                    seg = _closed_loop_segment(
                        mux, n_clients, ops_per_client, payload, timeout_s)
                cpu = time.process_time() - c0
                state, lats = seg["state"], seg["lats"]
                completed = state["done"] - state["failed"]
                return {
                    "cpu_us_per_op": cpu / total * 1e6,
                    "ops_s": round(completed / seg["elapsed_s"], 1)
                    if seg["elapsed_s"] else 0.0,
                    "p99_ms": round(_pct(lats, 99) * 1e3, 3),
                    "completed": completed,
                }

            def median(vals):
                s = sorted(vals)
                m = len(s) // 2
                return s[m] if len(s) % 2 else (s[m - 1] + s[m]) / 2

            segment(False)    # warmup: discarded (cold code paths, sockets)
            deltas = []
            on_segs, off_segs = [], []
            for i in range(rounds):
                first_off = bool(i % 2)        # alternate A/B, B/A order
                a = segment(first_off)
                b = segment(not first_off)
                on_seg, off_seg = (b, a) if first_off else (a, b)
                on_segs.append(on_seg)
                off_segs.append(off_seg)
                deltas.append(
                    (on_seg["cpu_us_per_op"] - off_seg["cpu_us_per_op"])
                    / off_seg["cpu_us_per_op"] * 100.0)

            def arm(segs):
                return {
                    "ops_s": median([s["ops_s"] for s in segs]),
                    "p99_ms": median([s["p99_ms"] for s in segs]),
                    "cpu_us_per_op": round(
                        median([s["cpu_us_per_op"] for s in segs]), 2),
                }

            return {
                "mode": "mux-overhead",
                "clients": n_clients,
                "ops_per_client": ops_per_client,
                "connections": n_conns,
                "rounds": rounds,
                "overhead_pct": round(max(0.0, median(deltas)), 2),
                "deltas_pct": [round(d, 2) for d in sorted(deltas)],
                "instruments_on": arm(on_segs),
                "instruments_off": arm(off_segs),
            }
        finally:
            if mux is not None:
                mux.close()
            server.stop()
            cluster.shutdown()


def run_mux_overload_pair(n_clients: int = 10000,
                          ops_per_client: int = 2,
                          n_conns: int = 8,
                          overload_queue_max: int = 64,
                          key_dist: str | None = None,
                          flash_crowd: str | None = None) -> dict:
    """The bench.py ``serving.async`` block: one clean-capacity run
    (queue limit ABOVE the client count: nothing sheds) and one
    overload run (tiny dispatch queue, one worker: the shed ladder must
    refuse work while goodput continues).  ``key_dist`` /
    ``flash_crowd`` overlay a key stream on the arrivals (fresh
    generator per arm: the streams stay independently reproducible)."""
    def mk_keys():
        if key_dist is None and flash_crowd is None:
            return None
        dist, s = parse_key_dist(key_dist or "uniform")
        return WorkloadKeys(
            n_keys=n_clients, dist=dist, zipf_s=s,
            flash=parse_flash_crowd(flash_crowd) if flash_crowd else None)
    capacity = run_mux_bench(n_clients, ops_per_client, n_conns,
                             queue_max=max(2 * n_clients, 2048),
                             keys=mk_keys())
    overload = run_mux_bench(min(n_clients, 2000), ops_per_client,
                             n_conns, queue_max=overload_queue_max,
                             op_threads=1, keys=mk_keys())
    return {
        "clients": capacity["clients"],
        "ops_s": capacity["ops_s"],
        "p99_ms": capacity["p99_ms"],
        "p50_ms": capacity["p50_ms"],
        "threads": capacity["threads"],
        "workload": capacity.get("workload"),
        "capacity": capacity,
        "overload": {
            "clients": overload["clients"],
            "ops_s": overload["ops_s"],
            "p99_ms": overload["p99_ms"],
            "shed_rate": overload["shed_rate"],
            "shed_retries": overload["shed_retries"],
            "server_shed": overload["server_shed"],
            "completed": overload["completed"],
        },
    }


def run_zero_copy_pair(n_clients: int = 256, ops_per_client: int = 4,
                       n_conns: int = 8,
                       payload_bytes: int = 65536) -> dict:
    """The bench.py ``serving.zero_copy`` block: the same closed-loop
    mux ping workload twice — the FUSED arm serializing payloads through
    the raw sideband segment (``ms_zero_copy=true``: one staging copy
    server-side, one materialize client-side) and the LEGACY arm forced
    through pickled frames (pickle + segment join on send, unpickle on
    receive, both directions).  The copy ledger resets around each arm,
    so each arm's ``copies_per_byte`` is exactly its own bytes-copied /
    bytes-served ratio — the number the perf gate caps absolutely on the
    fused arm and floors on the legacy arm (a legacy ratio below ~3
    would mean the ledger stopped seeing the copies, not that the
    legacy path got faster)."""
    from ceph_tpu.common import copy_ledger

    def arm(on: bool) -> dict:
        led = copy_ledger.ledger()
        led.reset()
        r = run_mux_bench(n_clients, ops_per_client, n_conns,
                          payload_bytes=payload_bytes,
                          queue_max=max(2 * n_clients, 2048),
                          conf_overrides={"ms_zero_copy": on},
                          distinct_payloads=True)
        snap = led.snapshot()
        return {"ops_s": r["ops_s"], "p50_ms": r["p50_ms"],
                "p99_ms": r["p99_ms"], "completed": r["completed"],
                "finished_in_time": r["finished_in_time"],
                "copies_per_byte": snap["copies_per_byte"],
                "copied": snap["copied"],
                "copied_total": snap["copied_total"],
                "served": snap["served"]}

    fused = arm(True)
    legacy = arm(False)
    return {
        "payload_bytes": payload_bytes,
        "clients": n_clients,
        "ops_per_client": ops_per_client,
        "fused": fused,
        "legacy": legacy,
        "copies_per_byte": fused["copies_per_byte"],
        "legacy_copies_per_byte": legacy["copies_per_byte"],
        "goodput_ratio": round(fused["ops_s"] / legacy["ops_s"], 3)
        if legacy["ops_s"] else 0.0,
    }


def main(argv=None) -> int:
    from ceph_tpu.utils.platform import honour_jax_platforms_env
    honour_jax_platforms_env()
    ap = argparse.ArgumentParser(
        prog="rados_bench", description=__doc__.splitlines()[0])
    ap.add_argument("--mode", choices=["closed", "open", "mux"],
                    default="closed")
    ap.add_argument("--ops", type=int, default=512,
                    help="closed loop: total ops to complete")
    ap.add_argument("--concurrency", type=int, default=64,
                    help="closed loop: logical clients in flight")
    ap.add_argument("--rate", type=float, default=1000.0,
                    help="open loop: offered arrival rate (ops/s)")
    ap.add_argument("--seconds", type=float, default=5.0,
                    help="open loop: arrival window")
    ap.add_argument("--op-size", default="4K")
    ap.add_argument("--chunk-size", default="1K")
    ap.add_argument("--k", type=int, default=4)
    ap.add_argument("--m", type=int, default=2)
    ap.add_argument("--plugin", default="jax_rs")
    ap.add_argument("--device", default="jax",
                    help="jax_rs device: jax|numpy|auto (jax measures the "
                         "real dispatch path the coalescer amortizes)")
    ap.add_argument("--technique", default="reed_sol_van")
    ap.add_argument("--batch-max-ops", type=int, default=None,
                    help="coalescer cap (default: osd_batch_max_ops)")
    ap.add_argument("--batch-max-delay-ms", type=float, default=None)
    ap.add_argument("--unbatched", action="store_true",
                    help="op-at-a-time baseline (batch_max_ops=1)")
    ap.add_argument("--compare", action="store_true",
                    help="run batched AND unbatched, report the speedup")
    ap.add_argument("--warmup", type=int, default=64,
                    help="warmup ops per engine (compiles size buckets)")
    ap.add_argument("--clients", type=int, default=10000,
                    help="mux mode: logical closed-loop sessions")
    ap.add_argument("--ops-per-client", type=int, default=2,
                    help="mux mode: RPCs each session completes")
    ap.add_argument("--conns", type=int, default=8,
                    help="mux mode: TCP connections carrying all sessions")
    ap.add_argument("--overload-queue-max", type=int, default=64,
                    help="mux mode: dispatch-queue limit for the overload "
                         "arm (tiny = heavy shedding)")
    ap.add_argument("--key-dist", default=None,
                    help="mux mode: key distribution over the keyspace — "
                         "uniform or zipf:<s> (e.g. zipf:1.2)")
    ap.add_argument("--flash-crowd", default=None,
                    help="mux mode: frac:start:dur — during the "
                         "[start, start+dur) slice of the run (progress "
                         "coordinates, 0..1), frac of arrivals hit the "
                         "0.1%% hot set (the cache-tier stress shape)")
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)

    if args.mode == "mux":
        result = run_mux_overload_pair(
            n_clients=args.clients, ops_per_client=args.ops_per_client,
            n_conns=args.conns,
            overload_queue_max=args.overload_queue_max,
            key_dist=args.key_dist, flash_crowd=args.flash_crowd)
        if args.as_json:
            print(json.dumps(result))
        else:
            w = sys.stdout.write
            w(f"mux capacity:  {result['clients']} clients over "
              f"{args.conns} conns  {result['ops_s']:.0f} ops/s  "
              f"p50 {result['p50_ms']:.3f} ms  "
              f"p99 {result['p99_ms']:.3f} ms  "
              f"threads {result['threads']}\n")
            ov = result["overload"]
            w(f"mux overload:  {ov['clients']} clients  "
              f"{ov['ops_s']:.0f} ops/s goodput  "
              f"p99 {ov['p99_ms']:.3f} ms  "
              f"shed-rate {ov['shed_rate']:.2%} "
              f"({ov['shed_retries']} refusals)\n")
            wl = result.get("workload")
            if wl:
                w(f"workload:      {wl['dist']}"
                  f"{':%g' % wl['zipf_s'] if wl['zipf_s'] else ''} over "
                  f"{wl['n_keys']} keys, {wl['distinct_keys']} touched"
                  + (f", flash {wl['flash']} hit {wl['flash_draws']}/"
                     f"{wl['keys_drawn']} draws onto {wl['hot_set']} "
                     f"hot keys" if wl["flash"] else "") + "\n")
        return 0

    from ceph_tpu.common import parse_size
    from ceph_tpu.exec import ServingEngine
    from ceph_tpu.exec.workload import (closed_loop,
                                        compare_batched_unbatched,
                                        make_payloads, open_loop)
    ec, sinfo = build_codec(args)
    op_bytes = parse_size(args.op_size)
    print(f"# k={args.k} m={args.m} chunk={sinfo.chunk_size} "
          f"op={op_bytes} plugin={args.plugin} device={args.device}",
          file=sys.stderr)

    if args.compare:
        result = compare_batched_unbatched(
            ec, sinfo, n_ops=args.ops, concurrency=args.concurrency,
            op_bytes=op_bytes, warmup_ops=args.warmup,
            batch_max_ops=args.batch_max_ops)
    else:
        engine = ServingEngine(
            ec_impl=ec, sinfo=sinfo, name="rados_bench",
            max_ops=max(1024, args.concurrency * 2),
            max_bytes=max(64 << 20, args.concurrency * op_bytes * 4),
            batch_max_ops=1 if args.unbatched else args.batch_max_ops,
            batch_max_delay_ms=args.batch_max_delay_ms).start()
        try:
            payloads = make_payloads(op_bytes)
            if args.warmup:
                closed_loop(engine, args.warmup,
                            min(args.concurrency, args.warmup), payloads)
            if args.mode == "closed":
                result = closed_loop(engine, args.ops, args.concurrency,
                                     payloads)
            else:
                result = open_loop(engine, args.rate, args.seconds,
                                   payloads)
        finally:
            engine.stop()

    if args.as_json:
        print(json.dumps(result))
    else:
        human(result, sys.stdout)
    return 0


if __name__ == "__main__":
    sys.exit(main())
