#!/usr/bin/env python3
"""rados_bench: open/closed-loop workload generator for the serving engine.

The serving-side sibling of ``rados bench`` (the cluster-level
write/seq bench lives at ``python -m ceph_tpu.bench.rados_bench``): this
tool drives CONCURRENT encode ops through ``ceph_tpu.exec.ServingEngine``
and reports throughput plus p50/p95/p99 latency — the numbers that decide
whether the op coalescer is earning its deadline.

    # closed loop, 64 clients, compare coalesced vs op-at-a-time:
    python tools/rados_bench.py --compare --concurrency 64 --ops 512

    # closed loop against one engine configuration:
    python tools/rados_bench.py --concurrency 64 --ops 1024 \
        --batch-max-ops 64 --op-size 16K --device jax

    # open loop at a fixed arrival rate (tail latency without
    # coordinated omission):
    python tools/rados_bench.py --mode open --rate 2000 --seconds 5

    # machine-readable:
    python tools/rados_bench.py --compare --json

``--unbatched`` pins ``batch_max_ops=1`` (every op is its own device
dispatch) — the baseline the coalesced number is judged against, on the
same device.
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def build_codec(args):
    from ceph_tpu.backend import StripeInfo
    from ceph_tpu.common import parse_size
    from ceph_tpu.plugins.registry import ErasureCodePluginRegistry
    profile = {"plugin": args.plugin, "k": str(args.k), "m": str(args.m),
               "technique": args.technique}
    if args.plugin == "jax_rs":
        profile["device"] = args.device
    ec = ErasureCodePluginRegistry.instance().factory(
        args.plugin, "", profile)
    return ec, StripeInfo(args.k, parse_size(args.chunk_size))


def human(result: dict, out) -> None:
    w = out.write
    if "batched" in result:
        for label in ("unbatched", "batched"):
            r = result[label]
            w(f"{label:>10}: {r['ops_s']:>9.1f} ops/s  "
              f"{r['mb_s']:>8.2f} MB/s  p50 {r['p50_ms']:.3f} ms  "
              f"p95 {r['p95_ms']:.3f} ms  p99 {r['p99_ms']:.3f} ms  "
              f"(mean batch {r['mean_batch_size']})\n")
        w(f"{'speedup':>10}: {result['speedup']}x coalesced vs "
          f"op-at-a-time\n")
        return
    w(f"Mode:               {result['mode']}\n")
    w(f"Ops completed:      {result['ops']}\n")
    if "rejected" in result:
        w(f"Ops rejected:       {result['rejected']}\n")
    w(f"Op size:            {result['op_bytes']}\n")
    w(f"Total time (s):     {result['elapsed_s']}\n")
    w(f"Throughput (ops/s): {result['ops_s']}\n")
    w(f"Bandwidth (MB/s):   {result['mb_s']}\n")
    w(f"Latency p50 (ms):   {result['p50_ms']}\n")
    w(f"Latency p95 (ms):   {result['p95_ms']}\n")
    w(f"Latency p99 (ms):   {result['p99_ms']}\n")
    w(f"Mean batch size:    {result['mean_batch_size']}\n")


def main(argv=None) -> int:
    from ceph_tpu.utils.platform import honour_jax_platforms_env
    honour_jax_platforms_env()
    ap = argparse.ArgumentParser(
        prog="rados_bench", description=__doc__.splitlines()[0])
    ap.add_argument("--mode", choices=["closed", "open"], default="closed")
    ap.add_argument("--ops", type=int, default=512,
                    help="closed loop: total ops to complete")
    ap.add_argument("--concurrency", type=int, default=64,
                    help="closed loop: logical clients in flight")
    ap.add_argument("--rate", type=float, default=1000.0,
                    help="open loop: offered arrival rate (ops/s)")
    ap.add_argument("--seconds", type=float, default=5.0,
                    help="open loop: arrival window")
    ap.add_argument("--op-size", default="4K")
    ap.add_argument("--chunk-size", default="1K")
    ap.add_argument("--k", type=int, default=4)
    ap.add_argument("--m", type=int, default=2)
    ap.add_argument("--plugin", default="jax_rs")
    ap.add_argument("--device", default="jax",
                    help="jax_rs device: jax|numpy|auto (jax measures the "
                         "real dispatch path the coalescer amortizes)")
    ap.add_argument("--technique", default="reed_sol_van")
    ap.add_argument("--batch-max-ops", type=int, default=None,
                    help="coalescer cap (default: osd_batch_max_ops)")
    ap.add_argument("--batch-max-delay-ms", type=float, default=None)
    ap.add_argument("--unbatched", action="store_true",
                    help="op-at-a-time baseline (batch_max_ops=1)")
    ap.add_argument("--compare", action="store_true",
                    help="run batched AND unbatched, report the speedup")
    ap.add_argument("--warmup", type=int, default=64,
                    help="warmup ops per engine (compiles size buckets)")
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)

    from ceph_tpu.common import parse_size
    from ceph_tpu.exec import ServingEngine
    from ceph_tpu.exec.workload import (closed_loop,
                                        compare_batched_unbatched,
                                        make_payloads, open_loop)
    ec, sinfo = build_codec(args)
    op_bytes = parse_size(args.op_size)
    print(f"# k={args.k} m={args.m} chunk={sinfo.chunk_size} "
          f"op={op_bytes} plugin={args.plugin} device={args.device}",
          file=sys.stderr)

    if args.compare:
        result = compare_batched_unbatched(
            ec, sinfo, n_ops=args.ops, concurrency=args.concurrency,
            op_bytes=op_bytes, warmup_ops=args.warmup,
            batch_max_ops=args.batch_max_ops)
    else:
        engine = ServingEngine(
            ec_impl=ec, sinfo=sinfo, name="rados_bench",
            max_ops=max(1024, args.concurrency * 2),
            max_bytes=max(64 << 20, args.concurrency * op_bytes * 4),
            batch_max_ops=1 if args.unbatched else args.batch_max_ops,
            batch_max_delay_ms=args.batch_max_delay_ms).start()
        try:
            payloads = make_payloads(op_bytes)
            if args.warmup:
                closed_loop(engine, args.warmup,
                            min(args.concurrency, args.warmup), payloads)
            if args.mode == "closed":
                result = closed_loop(engine, args.ops, args.concurrency,
                                     payloads)
            else:
                result = open_loop(engine, args.rate, args.seconds,
                                   payloads)
        finally:
            engine.stop()

    if args.as_json:
        print(json.dumps(result))
    else:
        human(result, sys.stdout)
    return 0


if __name__ == "__main__":
    sys.exit(main())
