"""Operator tooling (benchmarks, gates, reports).

A package so bench.py and the tests can import the reusable entry
points (``tools.rados_bench.run_mux_bench``, ``tools.perf_gate``)
without path hacks; each script remains directly runnable too.
"""
