"""Sweep RS GF-apply kernel variants on the attached TPU.

Explores the roofline levers from VERDICT r3 item 2: int8 MXU accumulation
(2x bf16 peak on v5e), block-diagonal coefficient stacking (lifts the
[32, 64] degenerate matmul to full [128, 256] MXU tiles), tile width, and
a pure-stream copy kernel as the bandwidth ceiling reference.

Usage: python tools/kernel_sweep.py [--quick]
"""
from __future__ import annotations

import argparse
import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")
from ceph_tpu.ops.pallas_kernels import expand_bits_plane_major  # noqa: E402
from ceph_tpu.ops import rs_kernels  # noqa: E402
from ceph_tpu.gf.matrix import cauchy1  # noqa: E402

from jax.experimental import pallas as pl


def chain_time(apply_fn, mat, data, reps=18, rounds=4):
    @jax.jit
    def run(M, D):
        def body(i, carry):
            out = apply_fn(M, carry)
            head = jax.lax.dynamic_slice(carry, (0, 0), out.shape)
            return jax.lax.dynamic_update_slice(
                carry, jax.lax.bitwise_xor(head, out), (0, 0))
        return jax.lax.fori_loop(0, reps, body, D).astype(jnp.int32).sum()
    _ = int(run(mat, data))
    best = 1e9
    for _ in range(rounds):
        t0 = time.perf_counter()
        _ = int(run(mat, data))
        best = min(best, time.perf_counter() - t0)
    return best


def per_op(apply_fn, mat, data, reps=18):
    t2 = chain_time(apply_fn, mat, data, 2)
    tb = chain_time(apply_fn, mat, data, reps)
    return max((tb - t2) / (reps - 2), 1e-9)


# -- variant kernels ---------------------------------------------------------

def _kernel_v1(bmat_ref, data_ref, out_ref, *, r, k, acc_dtype):
    """Current shape: one [8r, 8k] x [8k, T] dot."""
    d = data_ref[:].astype(jnp.int32)
    planes = [((d >> b) & 1) for b in range(8)]
    if acc_dtype == "bf16":
        bits = jnp.concatenate(planes, axis=0).astype(jnp.bfloat16)
        acc = jax.lax.dot_general(bmat_ref[:], bits, (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        acc = acc.astype(jnp.int32) & 1
    else:
        bits = jnp.concatenate(planes, axis=0).astype(jnp.int8)
        acc = jax.lax.dot_general(bmat_ref[:], bits, (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.int32)
        acc = acc & 1
    out = acc[0:r]
    for b in range(1, 8):
        out = out | (acc[b * r:(b + 1) * r] << b)
    out_ref[:] = out.astype(jnp.uint8)


def make_v1(mat, tile_n, acc_dtype):
    r, k = mat.shape
    bexp = expand_bits_plane_major(mat)
    bmat = jnp.asarray(bexp, dtype=jnp.bfloat16 if acc_dtype == "bf16"
                       else jnp.int8)

    def apply_fn(_m, data):
        n = data.shape[1]
        n_tiles = n // tile_n
        return pl.pallas_call(
            functools.partial(_kernel_v1, r=r, k=k, acc_dtype=acc_dtype),
            out_shape=jax.ShapeDtypeStruct((r, n), jnp.uint8),
            grid=(n_tiles,),
            in_specs=[pl.BlockSpec((8 * r, 8 * k), lambda i: (0, 0)),
                      pl.BlockSpec((k, tile_n), lambda i: (0, i))],
            out_specs=pl.BlockSpec((r, tile_n), lambda i: (0, i)),
        )(bmat, data)
    return apply_fn


def _kernel_bd(bmat_ref, d0, d1, d2, d3, o0, o1, o2, o3, *, r, k, acc_dtype,
               groups):
    """Block-diagonal: `groups` independent column tiles in one dot."""
    drefs = [d0, d1, d2, d3][:groups]
    orefs = [o0, o1, o2, o3][:groups]
    parts = []
    for dref in drefs:
        d = dref[:].astype(jnp.int32)
        parts.extend(((d >> b) & 1) for b in range(8))
    if acc_dtype == "bf16":
        bits = jnp.concatenate(parts, axis=0).astype(jnp.bfloat16)
        acc = jax.lax.dot_general(bmat_ref[:], bits, (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        acc = acc.astype(jnp.int32) & 1
    else:
        bits = jnp.concatenate(parts, axis=0).astype(jnp.int8)
        acc = jax.lax.dot_general(bmat_ref[:], bits, (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.int32)
        acc = acc & 1
    for g, oref in enumerate(orefs):
        base = g * 8 * r
        out = acc[base:base + r]
        for b in range(1, 8):
            out = out | (acc[base + b * r:base + (b + 1) * r] << b)
        oref[:] = out.astype(jnp.uint8)


def make_bd(mat, tile_n, acc_dtype, groups):
    r, k = mat.shape
    bexp = np.asarray(expand_bits_plane_major(mat))          # [8r, 8k]
    bd = np.zeros((groups * 8 * r, groups * 8 * k), dtype=np.uint8)
    for g in range(groups):
        bd[g * 8 * r:(g + 1) * 8 * r, g * 8 * k:(g + 1) * 8 * k] = bexp
    bmat = jnp.asarray(bd, dtype=jnp.bfloat16 if acc_dtype == "bf16"
                       else jnp.int8)

    def apply_fn(_m, data):
        n = data.shape[1]
        n_tiles = n // (tile_n * groups)
        in_specs = [pl.BlockSpec((groups * 8 * r, groups * 8 * k),
                                 lambda i: (0, 0))]
        for g in range(groups):
            in_specs.append(pl.BlockSpec(
                (k, tile_n), lambda i, _g=g: (0, i * groups + _g)))
        out_specs = [pl.BlockSpec((r, tile_n),
                                  lambda i, _g=g: (0, i * groups + _g))
                     for g in range(groups)]
        outs = pl.pallas_call(
            functools.partial(_kernel_bd, r=r, k=k, acc_dtype=acc_dtype,
                              groups=groups),
            out_shape=[jax.ShapeDtypeStruct((r, n), jnp.uint8)] * groups,
            grid=(n_tiles,),
            in_specs=in_specs,
            out_specs=out_specs,
        )(bmat, *([data] * groups))
        return outs[0]          # timing only; real impl merges groups
    return apply_fn


def _copy_kernel(d_ref, o_ref, *, r, k):
    o_ref[:] = d_ref[0:r]


def make_copy(mat, tile_n):
    """Bandwidth ceiling: read [k, T], write [r, T], zero compute."""
    r, k = mat.shape

    def apply_fn(_m, data):
        n = data.shape[1]
        return pl.pallas_call(
            functools.partial(_copy_kernel, r=r, k=k),
            out_shape=jax.ShapeDtypeStruct((r, n), jnp.uint8),
            grid=(n // tile_n,),
            in_specs=[pl.BlockSpec((k, tile_n), lambda i: (0, i))],
            out_specs=pl.BlockSpec((r, tile_n), lambda i: (0, i)),
        )(data)
    return apply_fn


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()

    k, m = 8, 4
    n = 64 * 1024 * 1024 // k                 # 64 MiB total, like bench.py
    rng = np.random.default_rng(0)
    data = jax.device_put(jnp.asarray(
        rng.integers(0, 256, size=(k, n), dtype=np.uint8)))
    mat = jnp.asarray(cauchy1(k, m), dtype=jnp.uint8)
    mib = k * n / 2**20

    print(f"device={jax.devices()[0]}  data {k}x{n} = {mib:.0f} MiB")

    def report(name, fn):
        try:
            t = per_op(fn, mat, data)
            print(f"{name:34s} {mib / t:10.0f} MiB/s")
        except Exception as e:
            print(f"{name:34s} FAILED: {str(e)[:120]}")

    tiles = [4096, 8192] if args.quick else [2048, 4096, 8192, 16384, 32768]
    report("copy-ceiling t=8192", make_copy(mat, 8192))
    report("copy-ceiling t=32768", make_copy(mat, 32768))
    for t in tiles:
        report(f"v1 bf16 t={t}", make_v1(mat, t, "bf16"))
    for t in tiles:
        report(f"v1 int8 t={t}", make_v1(mat, t, "int8"))
    for groups in (2, 4):
        for t in ([4096, 8192] if args.quick else [2048, 4096, 8192]):
            report(f"bd{groups} int8 t={t}", make_bd(mat, t, "int8", groups))
    report("bd4 bf16 t=4096", make_bd(mat, 4096, "bf16", 4))
    # XLA reference paths
    report("xla bitslice", lambda M, D: rs_kernels.gf_apply_bitslice(M, D))
    return 0


if __name__ == "__main__":
    sys.exit(main())
