#!/usr/bin/env python3
"""Roofline report: the per-executable device-efficiency table, post-hoc.

Renders the roofline ledger (common/roofline.py) from an artifact alone
— no live process required (the ts_report discipline).  Accepted inputs,
auto-detected:

- a bench.py JSON line (its ``efficiency`` block), or a driver
  ``BENCH_r*.json`` wrapper (``parsed.efficiency``);
- a flight-recorder bundle (its ``efficiency`` source — the full
  roofline snapshot);
- a raw ``roofline.snapshot()`` / ``device roofline`` JSON document.

For every executable: calls, modeled FLOPs/bytes, arithmetic intensity,
achieved GB/s and GFLOP/s over the measured dispatch seconds, percent of
the binding roofline peak, and the memory/compute-bound classification.

    python tools/roofline_report.py BENCH_r08.json
    python tools/roofline_report.py flight-....json --json

Stdlib-only, standalone on purpose (tools/trace_report.py's discipline).
"""
from __future__ import annotations

import argparse
import json
import sys


def extract(doc: dict) -> dict | None:
    """Find the efficiency payload in any accepted document shape:
    ``{peaks, executables, ...}`` with executables normalized to a list
    of rows each carrying an ``executable`` key."""
    if not isinstance(doc, dict):
        return None
    # driver wrapper -> bench line
    if isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    # bench line / flight bundle -> their efficiency block/source
    if isinstance(doc.get("efficiency"), dict):
        doc = doc["efficiency"]
    execs = doc.get("executables")
    if execs is None:
        return None
    if isinstance(execs, dict):              # snapshot shape: id -> rec
        rows = [dict(rec, executable=eid)
                for eid, rec in sorted(execs.items())]
    else:
        rows = [dict(r) for r in execs if isinstance(r, dict)]
    return {"peaks": doc.get("peaks") or {},
            "device": doc.get("device"),
            "totals": doc.get("totals"),
            "pct_of_peak": doc.get("pct_of_peak"),
            "executables": rows,
            "error": doc.get("error")}


def _fmt_qty(v: float) -> str:
    for unit in ("", "K", "M", "G", "T"):
        if abs(v) < 1000 or unit == "T":
            return f"{v:.1f}{unit}"
        v /= 1000.0
    return f"{v:.1f}T"                       # pragma: no cover


def render(data: dict, limit: int = 20) -> str:
    rows = sorted(data["executables"],
                  key=lambda r: r.get("seconds", 0.0), reverse=True)
    peaks = data["peaks"]
    lines = []
    head = []
    if data.get("device"):
        head.append(f"device={data['device']}")
    if peaks:
        head.append(f"peaks {peaks.get('flops', 0) / 1e12:.1f} TFLOP/s / "
                    f"{peaks.get('hbm_bytes_s', 0) / 1e9:.0f} GB/s "
                    f"({peaks.get('source')})")
    pct = data.get("pct_of_peak")
    if pct is None and isinstance(data.get("totals"), dict):
        pct = data["totals"].get("pct_of_peak")
    if pct is not None:
        head.append(f"aggregate {pct:.2f}% of peak")
    if head:
        lines.append("  ".join(head))
    lines.append(f"{'EXECUTABLE':<46} {'CALLS':>6} {'FLOPS':>8} "
                 f"{'BYTES':>8} {'AI':>7} {'GB/S':>8} {'GF/S':>8} "
                 f"{'%PEAK':>7} BOUND")
    for r in rows[:limit]:
        lines.append(
            f"{str(r.get('executable', '?'))[:46]:<46} "
            f"{int(r.get('calls', 0)):>6} "
            f"{_fmt_qty(float(r.get('flops', 0.0))):>8} "
            f"{_fmt_qty(float(r.get('bytes', 0.0))):>8} "
            f"{float(r.get('arithmetic_intensity', 0.0)):>7.2f} "
            f"{float(r.get('achieved_bytes_s', 0.0)) / 1e9:>8.3f} "
            f"{float(r.get('achieved_flops_s', 0.0)) / 1e9:>8.3f} "
            f"{float(r.get('pct_of_peak', 0.0)):>7.2f} "
            f"{r.get('bound', '?')}")
    if len(rows) > limit:
        lines.append(f"... {len(rows) - limit} more (raise --limit)")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="per-executable roofline table from a bench "
                    "artifact, flight bundle, or roofline snapshot")
    ap.add_argument("artifact", help="JSON document to render")
    ap.add_argument("--limit", type=int, default=20,
                    help="max executable rows (default 20)")
    ap.add_argument("--json", action="store_true",
                    help="emit the normalized payload as JSON")
    args = ap.parse_args(argv)

    with open(args.artifact) as f:
        doc = json.load(f)
    data = extract(doc)
    if data is None:
        print(f"error: no efficiency/roofline data in {args.artifact} "
              f"(expected a bench line with an 'efficiency' block, a "
              f"flight bundle, or a roofline snapshot)", file=sys.stderr)
        return 2
    if data.get("error") and not data["executables"]:
        print(f"error: artifact carries an efficiency error marker: "
              f"{data['error']}", file=sys.stderr)
        return 2
    try:
        if args.json:
            print(json.dumps(data))
        else:
            print(render(data, limit=args.limit))
    except BrokenPipeError:              # `... | head` is a normal use
        return 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
