#!/usr/bin/env python3
"""Render a Chrome trace-event file as a sorted self-time table.

Input: the JSON `trace dump` returns (``{"traceEvents": [...]}``, or a bare
event array) — save it with e.g.

    python - <<'PY'
    from ceph_tpu.common import default_context
    open("trace.json", "w").write(
        default_context().admin_socket.call_json("trace dump"))
    PY

then ``python tools/trace_report.py trace.json``.  Self time is each
span's duration minus the duration of spans nested inside it (same
pid/tid, contained by timestamps), i.e. where the wall clock actually
went — the number that ranks optimization targets, which total time
(double-counting every parent) cannot.
"""
from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict


def load_events(path: str) -> list[dict]:
    with open(path) as f:
        doc = json.load(f)
    events = doc["traceEvents"] if isinstance(doc, dict) else doc
    return [e for e in events if e.get("ph") == "X"]


def self_times(events: list[dict]) -> dict[str, dict]:
    """name -> {count, total_us, self_us}; nesting resolved per (pid, tid)
    with a containment stack sweep over ts-sorted complete events."""
    agg: dict[str, dict] = defaultdict(
        lambda: {"count": 0, "total_us": 0.0, "self_us": 0.0})
    by_track: dict[tuple, list[dict]] = defaultdict(list)
    for ev in events:
        by_track[(ev.get("pid"), ev.get("tid"))].append(ev)
    for track in by_track.values():
        # parents first at equal start times (longer duration wins)
        track.sort(key=lambda e: (e["ts"], -e.get("dur", 0.0)))
        stack: list[dict] = []          # enclosing spans, innermost last
        for ev in track:
            dur = float(ev.get("dur", 0.0))
            ts = float(ev["ts"])
            while stack and stack[-1]["ts"] + stack[-1].get("dur", 0.0) \
                    <= ts:
                stack.pop()
            if stack:                   # nested: charge the parent less
                parent = agg[stack[-1]["name"]]
                parent["self_us"] -= dur
            a = agg[ev["name"]]
            a["count"] += 1
            a["total_us"] += dur
            a["self_us"] += dur
            stack.append(ev)
    return dict(agg)


def render_table(agg: dict[str, dict], limit: int = 0) -> str:
    rows = sorted(agg.items(), key=lambda kv: kv[1]["self_us"],
                  reverse=True)
    if limit:
        rows = rows[:limit]
    width = max([len("span")] + [len(name) for name, _ in rows])
    lines = [f"{'span':<{width}}  {'count':>7}  {'total ms':>10}  "
             f"{'self ms':>10}  {'avg ms':>9}"]
    for name, a in rows:
        avg = a["total_us"] / a["count"] / 1e3 if a["count"] else 0.0
        lines.append(
            f"{name:<{width}}  {a['count']:>7}  "
            f"{a['total_us'] / 1e3:>10.3f}  {a['self_us'] / 1e3:>10.3f}  "
            f"{avg:>9.3f}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="sorted self-time table from a Chrome trace file")
    ap.add_argument("trace", help="trace JSON ({'traceEvents': ...} or [])")
    ap.add_argument("--limit", type=int, default=0,
                    help="show only the top N spans by self time")
    args = ap.parse_args(argv)
    events = load_events(args.trace)
    if not events:
        print("no complete ('ph': 'X') events in trace", file=sys.stderr)
        return 1
    print(render_table(self_times(events), args.limit))
    return 0


if __name__ == "__main__":
    sys.exit(main())
