#!/usr/bin/env python3
"""Render a Chrome trace-event file as a sorted self-time table.

Input: the JSON `trace dump` returns (``{"traceEvents": [...]}``, or a bare
event array) — save it with e.g.

    python - <<'PY'
    from ceph_tpu.common import default_context
    open("trace.json", "w").write(
        default_context().admin_socket.call_json("trace dump"))
    PY

then ``python tools/trace_report.py trace.json``.  Self time is each
span's duration minus the duration of spans nested inside it (same
pid/tid, contained by timestamps), i.e. where the wall clock actually
went — the number that ranks optimization targets, which total time
(double-counting every parent) cannot.  p50/p99 columns give each span
name's per-occurrence duration distribution — the serving-latency view
(a `serving.op` row's p99 IS the op tail) that a mean-only table hides.
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys
from collections import defaultdict

# THE shared nearest-rank definition (ceph_tpu/common/percentile.py),
# loaded by PATH so this tool stays standalone — no ceph_tpu package
# import (which would pull numpy).  The module itself is stdlib-only;
# tests/test_critpath.py's AST guard keeps local redefinitions out.
_PCTL_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          os.pardir, "ceph_tpu", "common",
                          "percentile.py")
_spec = importlib.util.spec_from_file_location("_ceph_tpu_percentile",
                                               _PCTL_PATH)
_pctl = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_pctl)


def load_doc(path: str) -> list[dict]:
    """Every event in the dump, metadata included (parsed once)."""
    with open(path) as f:
        doc = json.load(f)
    return doc["traceEvents"] if isinstance(doc, dict) else doc


def load_events(path: str) -> list[dict]:
    return [e for e in load_doc(path) if e.get("ph") == "X"]


def percentile_us(durs_us: list[float], q: float) -> float:
    """Nearest-rank percentile (q in [0, 100]) over raw durations —
    the shared definition from ceph_tpu/common/percentile.py."""
    return _pctl.percentile(durs_us, q)


def percentile_us_w(pairs: list[tuple], q: float) -> float:
    """Weighted nearest-rank over (duration_us, sample_weight) pairs —
    identical to :func:`percentile_us` when every weight is 1.0."""
    return _pctl.weighted_nearest_rank(sorted(pairs), q)


def event_weight(ev: dict) -> float:
    """The event's sample weight (1/rate stamped by the tracer's head
    sampler; 1.0 for unsampled-era and promoted events)."""
    try:
        w = float(ev.get("args", {}).get("sample_weight", 1.0))
    except (TypeError, ValueError):
        return 1.0
    return w if w > 0.0 else 1.0


def self_times(events: list[dict]) -> dict[str, dict]:
    """name -> {count, weight, total_us, self_us, durs_us, wdurs};
    nesting resolved per (pid, tid) with a containment stack sweep over
    ts-sorted complete events.  ``durs_us`` holds every occurrence's
    total duration (the p50/p99 source); ``wdurs`` pairs each with its
    sample weight and ``weight`` sums them (the de-biased op-count
    estimate for head-sampled dumps)."""
    agg: dict[str, dict] = defaultdict(
        lambda: {"count": 0, "weight": 0.0, "total_us": 0.0,
                 "self_us": 0.0, "durs_us": [], "wdurs": []})
    by_track: dict[tuple, list[dict]] = defaultdict(list)
    for ev in events:
        by_track[(ev.get("pid"), ev.get("tid"))].append(ev)
    for track in by_track.values():
        # parents first at equal start times (longer duration wins)
        track.sort(key=lambda e: (e["ts"], -e.get("dur", 0.0)))
        stack: list[dict] = []          # enclosing spans, innermost last
        for ev in track:
            dur = float(ev.get("dur", 0.0))
            ts = float(ev["ts"])
            while stack and stack[-1]["ts"] + stack[-1].get("dur", 0.0) \
                    <= ts:
                stack.pop()
            if stack:                   # nested: charge the parent less
                parent = agg[stack[-1]["name"]]
                parent["self_us"] -= dur
            w = event_weight(ev)
            a = agg[ev["name"]]
            a["count"] += 1
            a["weight"] += w
            a["total_us"] += dur
            a["self_us"] += dur
            a["durs_us"].append(dur)
            a["wdurs"].append((dur, w))
            stack.append(ev)
    return dict(agg)


def is_sampled(agg: dict[str, dict]) -> bool:
    """True when any row carries a non-unit sample weight (the dump came
    from a head-sampled tracer and percentiles are weight-de-biased)."""
    return any(abs(a.get("weight", a["count"]) - a["count"]) > 1e-9
               for a in agg.values())


def render_table(agg: dict[str, dict], limit: int = 0) -> str:
    rows = sorted(agg.items(), key=lambda kv: kv[1]["self_us"],
                  reverse=True)
    if limit:
        rows = rows[:limit]
    width = max([len("span")] + [len(name) for name, _ in rows])
    lines = []
    if is_sampled(agg):
        est = round(sum(a.get("weight", a["count"]) for _n, a in rows))
        n = sum(a["count"] for _n, a in rows)
        lines.append(f"sampled trace: p50/p99 weighted by sample_weight "
                     f"(~{est} ops estimated from {n} recorded spans)")
    lines.append(f"{'span':<{width}}  {'count':>7}  {'total ms':>10}  "
                 f"{'self ms':>10}  {'avg ms':>9}  {'p50 ms':>9}  "
                 f"{'p99 ms':>9}")
    for name, a in rows:
        avg = a["total_us"] / a["count"] / 1e3 if a["count"] else 0.0
        pairs = a.get("wdurs") or [(d, 1.0) for d in a.get("durs_us", [])]
        lines.append(
            f"{name:<{width}}  {a['count']:>7}  "
            f"{a['total_us'] / 1e3:>10.3f}  {a['self_us'] / 1e3:>10.3f}  "
            f"{avg:>9.3f}  {percentile_us_w(pairs, 50) / 1e3:>9.3f}  "
            f"{percentile_us_w(pairs, 99) / 1e3:>9.3f}")
    return "\n".join(lines)


def render_json(agg: dict[str, dict], limit: int = 0) -> str:
    """Machine-readable twin of the text table (CI/BENCH tooling was
    scraping the text): same rows, same order, explicit units."""
    rows = sorted(agg.items(), key=lambda kv: kv[1]["self_us"],
                  reverse=True)
    if limit:
        rows = rows[:limit]
    spans = []
    for name, a in rows:
        pairs = a.get("wdurs") or [(d, 1.0) for d in a.get("durs_us", [])]
        spans.append({
            "name": name,
            "count": a["count"],
            "est_count": round(a.get("weight", a["count"]), 1),
            "total_ms": round(a["total_us"] / 1e3, 6),
            "self_ms": round(a["self_us"] / 1e3, 6),
            "avg_ms": round(a["total_us"] / a["count"] / 1e3, 6)
            if a["count"] else 0.0,
            "p50_ms": round(percentile_us_w(pairs, 50) / 1e3, 6),
            "p99_ms": round(percentile_us_w(pairs, 99) / 1e3, 6),
        })
    return json.dumps({"spans": spans, "num_spans": len(spans),
                       "sampled": is_sampled(agg)})


def _track_names(all_events: list[dict]) -> dict:
    """pid -> daemon track name from the stitched dump's process_name
    metadata events (tracer.Tracer.dump(stitched=True))."""
    return {e["pid"]: e["args"]["name"] for e in all_events
            if e.get("ph") == "M" and e.get("name") == "process_name"}


def trace_tree(events: list[dict], trace_id: int,
               tracks: dict | None = None) -> list[str]:
    """Render ONE distributed trace as an indented span tree — the
    'where did this 1 MiB write spend its 4 ms' view.  Spans join on the
    trace/span ids the tracer stamps into event args; each line carries
    the daemon track, so a client op reads as client -> primary ->
    remote shards with per-hop durations."""
    tracks = tracks or {}
    spans = [e for e in events
             if e.get("args", {}).get("trace_id") == trace_id]
    if not spans:
        return [f"no spans for trace {trace_id}"]
    by_parent: dict[int, list[dict]] = defaultdict(list)
    ids = {e["args"]["span_id"] for e in spans}
    for e in spans:
        parent = e["args"].get("parent_span_id", 0)
        by_parent[parent if parent in ids else 0].append(e)
    for kids in by_parent.values():
        kids.sort(key=lambda e: e["ts"])
    lines = [f"trace {trace_id} ({len(spans)} spans, "
             f"{len({e.get('pid') for e in spans})} tracks)"]

    def walk(parent: int, depth: int) -> None:
        for e in by_parent.get(parent, ()):
            track = tracks.get(e.get("pid"), str(e.get("pid")))
            owner = e["args"].get("owner") or e["args"].get("op_class", "")
            extra = f" [{owner}]" if owner else ""
            lines.append(
                f"{'  ' * depth}{e['name']:<{max(1, 40 - 2 * depth)}} "
                f"{e.get('dur', 0.0) / 1e3:>9.3f} ms  @{track}{extra}")
            walk(e["args"]["span_id"], depth + 1)
    walk(0, 1)
    return lines


def list_traces(events: list[dict]) -> list[str]:
    """Traces present in the dump, largest root span first."""
    roots: dict[int, dict] = {}
    counts: dict[int, int] = defaultdict(int)
    for e in events:
        args = e.get("args", {})
        tid = args.get("trace_id")
        if tid is None:
            continue
        counts[tid] += 1
        if args.get("parent_span_id", 0) == 0:
            top = roots.get(tid)
            if top is None or e.get("dur", 0) > top.get("dur", 0):
                roots[tid] = e
    rows = sorted(roots.items(),
                  key=lambda kv: kv[1].get("dur", 0.0), reverse=True)
    out = [f"{'trace':>8}  {'spans':>6}  {'root ms':>9}  root"]
    for tid, root in rows:
        out.append(f"{tid:>8}  {counts[tid]:>6}  "
                   f"{root.get('dur', 0.0) / 1e3:>9.3f}  {root['name']}")
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="sorted self-time table from a Chrome trace file")
    ap.add_argument("trace", help="trace JSON ({'traceEvents': ...} or [])")
    ap.add_argument("--limit", type=int, default=0,
                    help="show only the top N spans by self time")
    ap.add_argument("--json", action="store_true",
                    help="emit the table as one JSON document instead of "
                         "text (same rows/order)")
    ap.add_argument("--trace-id", type=int, default=None,
                    help="render ONE distributed trace as a cross-daemon "
                         "span tree instead of the table")
    ap.add_argument("--traces", action="store_true",
                    help="list the distributed traces in the dump")
    args = ap.parse_args(argv)
    all_events = load_doc(args.trace)
    events = [e for e in all_events if e.get("ph") == "X"]
    if args.traces:
        print("\n".join(list_traces(events)))
        return 0
    if args.trace_id is not None:
        print("\n".join(trace_tree(events, args.trace_id,
                                   _track_names(all_events))))
        return 0
    if not events:
        # both modes keep the nonzero exit: a trace that captured
        # nothing is a failure signal CI must not green on
        if args.json:
            print(json.dumps({"spans": [], "num_spans": 0,
                              "error": "no complete ('ph': 'X') events "
                                       "in trace"}))
        else:
            print("no complete ('ph': 'X') events in trace",
                  file=sys.stderr)
        return 1
    agg = self_times(events)
    if args.json:
        print(render_json(agg, args.limit))
    else:
        print(render_table(agg, args.limit))
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:         # | head closed the pipe: not an error
        sys.exit(0)
