#!/usr/bin/env python3
"""Generate the erasure-code non-regression corpus.

Mirror of the reference's corpus scheme (reference:
src/test/erasure-code/ceph_erasure_code_non_regression.cc — writes chunk
files for a fixed pseudo-random payload per (plugin, profile) and re-checks
them across versions via
qa/workunits/erasure-code/encode-decode-non-regression.sh:19-40; the
archived corpus is the ceph-erasure-code-corpus submodule).  Here the
corpus records SHA-256 digests of every chunk instead of raw chunk files —
equally binding for bit-stability, kilobytes instead of megabytes in git.

Run from the repo root to (re)generate tests/golden/ec_corpus.json; the
committed file is what tests/test_ec_corpus.py replays.  Only add entries;
changing an existing digest is an encoding break.
"""
import hashlib
import json
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
OUT = os.path.join(REPO, "tests", "golden", "ec_corpus.json")

PAYLOAD_SIZE = 31116      # deliberately unaligned (forces padding paths)
PAYLOAD_SEED = 0xEC

PROFILES = [
    ("jax_rs", {"k": "2", "m": "1", "technique": "reed_sol_van"}),
    ("jax_rs", {"k": "4", "m": "2", "technique": "reed_sol_van"}),
    ("jax_rs", {"k": "8", "m": "4", "technique": "reed_sol_van"}),
    ("jax_rs", {"k": "10", "m": "4", "technique": "reed_sol_van"}),
    ("jax_rs", {"k": "4", "m": "2", "technique": "cauchy"}),
    ("jax_rs", {"k": "8", "m": "4", "technique": "cauchy"}),
    ("jax_rs", {"k": "6", "m": "3", "technique": "vandermonde"}),
    ("jax_rs", {"k": "4", "m": "2", "technique": "reed_sol_van",
                "mapping": "_DDD_D"}),
    ("jerasure", {"k": "4", "m": "2", "technique": "liberation",
                  "w": "7", "packetsize": "8"}),
    ("jerasure", {"k": "4", "m": "2", "technique": "blaum_roth",
                  "w": "6", "packetsize": "8"}),
    ("jerasure", {"k": "6", "m": "2", "technique": "liber8tion",
                  "packetsize": "8"}),
    ("jerasure", {"k": "4", "m": "3", "technique": "reed_sol_van",
                  "w": "16", "packetsize": "8"}),
    ("jerasure", {"k": "4", "m": "2", "technique": "cauchy_good",
                  "w": "32", "packetsize": "4"}),
    ("cpp_rs", {"k": "4", "m": "2", "technique": "reed_sol_van"}),
    ("cpp_rs", {"k": "8", "m": "4", "technique": "cauchy"}),
    ("xor", {"k": "3", "m": "1"}),
    ("shec", {"k": "4", "m": "3", "c": "2"}),
    ("lrc", {"k": "4", "m": "2", "l": "3"}),
    ("clay", {"k": "4", "m": "2", "d": "5",
              "scalar_mds": "jax_rs"}),
]


def payload() -> bytes:
    rng = np.random.default_rng(PAYLOAD_SEED)
    return rng.integers(0, 256, size=PAYLOAD_SIZE, dtype=np.uint8).tobytes()


def entry_name(plugin: str, profile: dict) -> str:
    parts = "_".join(f"{k}={v}" for k, v in sorted(profile.items())
                     if k != "plugin")
    return f"{plugin}/{parts}"


def main() -> int:
    from ceph_tpu.plugins.registry import ErasureCodePluginRegistry
    reg = ErasureCodePluginRegistry.instance()
    data = payload()
    corpus = {"payload_seed": PAYLOAD_SEED, "payload_size": PAYLOAD_SIZE,
              "entries": {}}
    for plugin, profile in PROFILES:
        prof = dict(profile)
        if plugin in ("jax_rs", "clay"):
            prof.setdefault("device", "numpy")
        ec = reg.factory(plugin, "", prof)
        n = ec.get_chunk_count()
        encoded = ec.encode(set(range(n)), data)
        digests = {str(i): hashlib.sha256(
            np.ascontiguousarray(encoded[i]).tobytes()).hexdigest()
            for i in sorted(encoded)}
        corpus["entries"][entry_name(plugin, profile)] = {
            "plugin": plugin,
            "profile": profile,
            "chunk_size": int(encoded[0].nbytes),
            "chunk_sha256": digests,
        }
        print(f"{entry_name(plugin, profile)}: {n} chunks x "
              f"{encoded[0].nbytes}")
    with open(OUT, "w") as f:
        json.dump(corpus, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"wrote {OUT}: {len(corpus['entries'])} entries")
    return 0


if __name__ == "__main__":
    sys.exit(main())
