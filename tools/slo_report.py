#!/usr/bin/env python3
"""slo_report: attribution + burn tables from an artifact, no cluster.

The read-side twin of ``tools/roofline_report.py`` for the latency
layer (ISSUE 10): given any artifact carrying SLO/critical-path data,
render the per-class p99 attribution table ("client p99 = 41 ms: 62%
batch_delay, 21% device, 9% wire") and, when objectives were
configured, the burn/budget table — so "which phase blew the budget"
is answered post-hoc, from the file alone.

Inputs, auto-detected:

- a ``bench.py`` JSON line (or a driver ``BENCH_r*.json`` wrapper, via
  its ``parsed`` field) — uses the ``slo`` block;
- a flight-recorder bundle (``flight-*.json``) — uses its ``slo``
  source (the SLO status + full critical-path ledger snapshot the
  WARN/ERR auto-capture rides);
- a raw ``trace dump`` (Chrome trace-event JSON) — folds the stitched
  traces through ``ceph_tpu/common/critpath.py`` right here (the
  module is stdlib-only and loaded by PATH, so this tool stays
  standalone).

    python tools/slo_report.py BENCH_r11.json
    python tools/slo_report.py DATA_DIR/flight/flight-...-SLO_BURN.json
    python tools/slo_report.py trace.json --json
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_by_path(rel: str, name: str):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, rel))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# critpath is stdlib-only and path-loadable by design: its
# format_phase_mix is THE phase-mix rendering, shared with the live
# `ceph slo status` table so the two can never drift
_critpath = _load_by_path("ceph_tpu/common/critpath.py",
                          "_ceph_tpu_critpath")
_phases_line = _critpath.format_phase_mix


def from_bench_line(line: dict) -> dict:
    """Normalize a bench line's ``slo`` block into the report shape."""
    block = line.get("slo")
    if not isinstance(block, dict):
        raise ValueError("artifact has no `slo` block")
    classes: dict = {}
    burn: dict = {}
    for cls, entry in block.items():
        if not isinstance(entry, dict) or "p99_ms" not in entry:
            continue
        classes[cls] = {"p99_ms": entry["p99_ms"],
                        "ops": entry.get("ops", 0),
                        "phases": entry.get("phases", {})}
        if "budget_remaining" in entry:
            burn[cls] = {
                "objective_p99_ms": entry.get("objective_p99_ms"),
                "burn_fast": entry.get("burn_fast"),
                "burn_slow": entry.get("burn_slow"),
                "budget_remaining": entry["budget_remaining"]}
    return {"source": "bench", "device": block.get("device"),
            "classes": classes, "burn": burn}


def from_flight_bundle(doc: dict) -> dict:
    """Normalize a flight bundle's ``slo`` source."""
    src = doc.get("slo")
    if not isinstance(src, dict) or "slo" not in src:
        raise ValueError("bundle has no `slo` source")
    status = src["slo"]
    classes: dict = {}
    for cls, summary in (status.get("attribution") or {}).items():
        if summary:
            classes[cls] = {"p99_ms": summary["p99_ms"],
                            "ops": summary["ops"],
                            "phases": summary["phases"]}
    burn: dict = {}
    for cls, s in (status.get("objectives") or {}).items():
        burn[cls] = {"objective_p99_ms": s["objective_p99_ms"],
                     "burn_fast": s["fast"]["burn"],
                     "burn_slow": s["slow"]["burn"],
                     "budget_remaining": s["budget_remaining"]}
    return {"source": "flight", "reason": doc.get("reason"),
            "classes": classes, "burn": burn}


def from_trace_dump(doc) -> dict:
    """Fold a raw trace dump through the critical-path extractor."""
    critpath = _critpath
    pctl = _load_by_path("ceph_tpu/common/percentile.py",
                         "_ceph_tpu_percentile")
    events = doc["traceEvents"] if isinstance(doc, dict) else doc
    per_class: dict[str, list[dict]] = {}
    for _tid, spans in sorted(critpath.group_traces(events).items()):
        rec = critpath.decompose(spans)
        if rec is not None:
            per_class.setdefault(rec["op_class"], []).append(rec)
    classes: dict = {}
    sampled = False
    for cls, recs in sorted(per_class.items()):
        # sample-weight de-bias (tracer head sampling, ISSUE 18): each
        # record stands for w ops; percentiles walk cumulative weight
        # and phase fractions scale by it, so a 1%-sampled dump reports
        # the same rates an unsampled one would
        pairs = sorted((r["total_s"], r.get("w", 1.0)) for r in recs)
        wsum = sum(w for _v, w in pairs)
        if any(w != 1.0 for _v, w in pairs):
            sampled = True
        agg: dict[str, float] = {}
        for r in recs:
            rw = r.get("w", 1.0)
            for p, v in r["phases"].items():
                agg[p] = agg.get(p, 0.0) + v * rw
        whole = sum(agg.values())
        classes[cls] = {
            "p99_ms": round(
                pctl.weighted_nearest_rank(pairs, 99) * 1e3, 3),
            "ops": len(recs),
            "weighted_ops": round(wsum, 1),
            "phases": {p: round(v / whole, 4) if whole else 0.0
                       for p, v in agg.items()}}
    return {"source": "trace", "sampled": sampled, "classes": classes,
            "burn": {}}


def build_report(doc) -> dict:
    """Auto-detect the artifact shape and normalize it."""
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]                        # BENCH_r wrapper
    if isinstance(doc, dict) and "slo" in doc and \
            isinstance(doc["slo"], dict) and "slo" in doc["slo"]:
        return from_flight_bundle(doc)
    if isinstance(doc, dict) and "slo" in doc:
        return from_bench_line(doc)
    if isinstance(doc, list) or (isinstance(doc, dict)
                                 and "traceEvents" in doc):
        return from_trace_dump(doc)
    raise ValueError("unrecognized artifact: need a bench line with an "
                     "`slo` block, a flight bundle with an `slo` "
                     "source, or a trace dump")


def render(report: dict) -> str:
    lines = [f"latency attribution ({report['source']} artifact):"]
    if report.get("sampled"):
        lines.append("  (head-sampled dump: percentiles and phase mixes "
                     "weighted by sample_weight)")
    if not report["classes"]:
        lines.append("  no per-class records")
    for cls, entry in sorted(report["classes"].items()):
        lines.append(f"  {cls} p99 = {entry['p99_ms']:.1f} ms "
                     f"({entry['ops']} ops): "
                     f"{_phases_line(entry['phases'])}")
    if report["burn"]:
        lines.append("error budgets:")
        lines.append(f"  {'class':<10} {'p99 obj':>9} {'burn(fast)':>10} "
                     f"{'burn(slow)':>10} {'budget left':>11}")
        for cls, b in sorted(report["burn"].items()):
            obj = b.get("objective_p99_ms")
            fast, slow = b.get("burn_fast"), b.get("burn_slow")
            lines.append(
                f"  {cls:<10} "
                f"{(f'{obj:.1f}ms' if obj is not None else '-'):>9} "
                f"{(f'{fast:.1f}x' if fast is not None else '-'):>10} "
                f"{(f'{slow:.1f}x' if slow is not None else '-'):>10} "
                f"{100 * b['budget_remaining']:>10.0f}%")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="render SLO attribution/burn tables from a bench "
                    "line, flight bundle, or trace dump")
    ap.add_argument("artifact")
    ap.add_argument("--json", action="store_true",
                    help="emit the normalized report as JSON")
    args = ap.parse_args(argv)
    with open(args.artifact) as f:
        doc = json.load(f)
    try:
        report = build_report(doc)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(report))
    else:
        print(render(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
