#!/usr/bin/env python
"""chaos_run: a seeded fault-injection campaign against a real TCP cluster.

The reproducible harness the thrash soaks improvise per-test (ISSUE 9;
the role qa/tasks/ceph_manager.py's Thrasher plays in the reference):
ONE seed drives every fault plane against a live ``MiniCluster`` served
over real sockets, and the campaign asserts the self-healing invariants
while it runs:

1. **Faulted traffic** — puts/gets through ``TcpRados`` while the server
   injects connection resets, black-holed requests, truncated frames and
   send delays, the bus reorders/duplicates, and stores stall reads.
   Every ACKED write must read back intact (reconnect + resend + reqid
   dedup make the acks honest).
2. **Flapping OSD** — one OSD cycles down/up through the monitor until
   flap damping trips: the boot is REFUSED, ``OSD_FLAPPING`` raises, an
   operator clear + boot brings it back and the check clears.
3. **Device breaker** — injected dispatch failures trip the codec
   pipeline's circuit breaker: batches keep succeeding through the sync
   host fallback (bitwise-identical parity), ``DEVICE_DEGRADED`` raises;
   with injection off, the half-open probe re-closes and health clears.
4. **Drain** — recovery reservations drain to zero and every acked
   write verifies, through the TCP client AND the local surface.

Two runs with the same seed produce the same injected-event digest —
the reproducibility receipt printed in the report.

Usage:
    python tools/chaos_run.py [--seed N] [--ops N] [--json FILE]
"""
from __future__ import annotations

import argparse
import json
import random
import shutil
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

K, M = 2, 1
CHUNK = 256
STRIPE = K * CHUNK

PROFILE = {"plugin": "jax_rs", "k": str(K), "m": str(M),
           "device": "numpy", "technique": "reed_sol_van"}


def _campaign_context():
    from ceph_tpu.common import Context
    return Context(overrides={
        # short timelines so the campaign heals in seconds, not minutes
        "ms_rpc_timeout": 8.0,
        "ms_rpc_retry_attempts": 4,
        "ms_reconnect_backoff_base": 0.01,
        "ms_reconnect_backoff_cap": 0.05,
        "osd_markdown_count": 3,
        "osd_markdown_window": 1000.0,
        "pipeline_breaker_threshold": 2,
        "pipeline_breaker_cooldown": 0.05,
        # an impossible latency objective: EVERY client op in the
        # faulted window burns budget, so SLO_BURN deterministically
        # raises while traffic flows and clears once it drains past the
        # (shortened) windows — the ISSUE-10 raise/heal receipt
        "slo_client_p99_ms": 0.001,
        "slo_client_target": 0.9,
        "slo_fast_window": 2.0,
        "slo_slow_window": 4.0,
        "slo_min_ops": 4,
    })


def _health_checks(cluster) -> set[str]:
    return set(cluster.health().get("checks", ()))


def _tier_phase(cluster, mon, cct, base_pid, seed, ops, rng, now,
                health_seen, say) -> dict:
    """Cache tiering under chaos (tier/): a flash-crowd key stream
    writes back through a replicated hot tier, the TIER_* checks raise
    and clear, then TWO acting OSDs of one cache PG die — every read
    still answers (degrading to base-pool proxies for the dead PG, the
    no-loss invariant), and hits resume after the OSDs boot back."""
    from tools.rados_bench import WorkloadKeys

    cct.conf.set("tier_promote_min_recency", 1)
    cache = cluster.create_replicated_pool(
        "chaos_cache", size=3, pg_num=4,
        params={"hit_set_count": "2", "hit_set_period": "16"})
    svc = cluster.create_tier(cache, base_pid)

    # flash crowd: zipf-skewed keys, half the mid-campaign arrivals
    # collapsing onto the hottest 10% of the key space
    keys = WorkloadKeys(n_keys=24, dist="zipf", zipf_s=1.1,
                        flash=(0.5, 0.25, 0.5), hot_frac=0.1,
                        seed=seed, prefix="t")
    tier_model: dict[str, bytes] = {}
    n_ops = max(40, ops)
    for i in range(n_ops):
        oid = keys.key(i / n_ops)
        if oid not in tier_model or rng.random() < 0.3:
            data = rng.randbytes(STRIPE)
            svc.write(oid, data)                # acked writeback
            tier_model[oid] = data
        else:
            assert svc.read(oid) == tier_model[oid], \
                f"tier read of acked {oid} diverged"
    assert svc.stats()["counters"]["hit"] > 0

    # TIER_FLUSH_BACKLOG: two zero-budget agent passes end over the
    # (tightened) high-dirty watermark, then a funded pass drains it
    cct.conf.set("tier_target_max_objects", 4 * len(svc.resident()))
    cct.conf.set("tier_dirty_ratio_high", 0.01)
    cct.conf.set("tier_dirty_ratio_low", 0.0)
    svc.agent.tick(max_ops=0)
    svc.agent.tick(max_ops=0)
    checks = _health_checks(cluster)
    health_seen |= checks
    assert "TIER_FLUSH_BACKLOG" in checks, \
        f"starved tier agent did not raise a flush backlog: {checks}"
    for _ in range(10):
        if svc.agent.tick(max_ops=64)["dirty_ratio"] == 0.0:
            break
    assert "TIER_FLUSH_BACKLOG" not in _health_checks(cluster), \
        "TIER_FLUSH_BACKLOG did not clear after the dirty set drained"

    # TIER_FULL: residency at target raises, a hard-full pass clears
    cct.conf.set("tier_target_max_objects", max(1, len(svc.resident())))
    checks = _health_checks(cluster)
    health_seen |= checks
    assert "TIER_FULL" in checks, f"full tier did not raise: {checks}"
    svc.agent.tick(max_ops=256)
    assert "TIER_FULL" not in _health_checks(cluster), \
        "TIER_FULL did not clear after the agent evicted"
    cct.conf.set("tier_target_max_objects", 256)   # roomy again: the
    # death drill below re-promotes, and that churn must not re-trip
    # the full watermark we just proved clears

    # tier OSD death: kill one cache PG's ENTIRE acting set (a single
    # surviving replica still serves reads, so whole-set death is what
    # forces the proxy degradation).  The victims must leave every base
    # PG at most one member short (EC k=2 of 3 stays readable) and
    # every other cache PG a survivor (replicated reads need one)
    target_g = victims = None
    for g in cluster.pools[cache]["pgs"].values():
        trio = set(g.acting)
        safe = all(len(trio & set(og.acting)) <= 1
                   for og in cluster.pools[base_pid]["pgs"].values()) \
            and all(len(trio & set(og.acting)) <= 2
                    for og in cluster.pools[cache]["pgs"].values()
                    if og is not g)
        if safe:
            target_g, victims = g, tuple(g.acting)
            break
    assert target_g is not None, "no safe victim set for tier OSD death"
    affected = sorted(o for o in tier_model
                      if cluster.pg_group(cache, o) is target_g)
    if not affected:
        # the skewed key stream missed the one safe PG: pin a couple of
        # acked writebacks onto it, flushed CLEAN before the deaths (a
        # dirty object whose only copies die with the cache PG is the
        # loss writeback mode legitimately cannot prevent)
        for j in range(256):
            oid = f"pin{j:04d}"
            if cluster.pg_group(cache, oid) is target_g:
                data = rng.randbytes(STRIPE)
                svc.write(oid, data)
                tier_model[oid] = data
                affected.append(oid)
                if len(affected) >= 2:
                    break
        assert affected, "could not pin objects onto the victim PG"
        for oid in affected:
            svc.flush(oid)

    hosts = {o: o // 3 for o in range(9)}
    t = now + 100.0
    for v in victims:
        reps = [o for o in range(9)
                if o not in victims and hosts[o] != hosts[v]]
        rep_a = reps[0]
        rep_b = next(o for o in reps if hosts[o] != hosts[rep_a])
        mon.prepare_failure(v, rep_a, failed_since=t - 25.0, now=t)
        mon.prepare_failure(v, rep_b, failed_since=t - 25.0, now=t)
    mon.propose_pending(t)
    assert all(cluster.osdmap.is_down(v) for v in victims)
    health_seen |= _health_checks(cluster)

    # every acked tier write still answers: resident-on-dead-PG reads
    # degrade to base proxies, nothing blocks, nothing is lost
    pre_proxy = svc.stats()["counters"]["proxy_read"]
    for oid, want in sorted(tier_model.items()):
        assert svc.read(oid) == want, \
            f"acked tier write {oid} lost under tier OSD death"
    degraded_proxies = svc.stats()["counters"]["proxy_read"] - pre_proxy
    assert degraded_proxies >= len(affected), \
        f"dead-PG reads did not proxy: {degraded_proxies} proxies " \
        f"for {len(affected)} affected objects"

    # heal: boot the victims back, then hits resume on the healed PG
    for v in victims:
        assert mon.osd_boot(v, now=t + 5.0), f"osd.{v} re-boot refused"
    mon.propose_pending(t + 5.0)
    cluster.deliver_all()
    assert all(cluster.osdmap.is_up(v) for v in victims)
    pre_hit = svc.stats()["counters"]["hit"]
    for _ in range(2):                       # pass 1 re-promotes evicted
        for oid in affected:                 # copies, pass 2 hits
            assert svc.read(oid) == tier_model[oid]
    assert svc.stats()["counters"]["hit"] > pre_hit, \
        "healed cache PG never served a hit again"
    final = _health_checks(cluster)
    assert not any(k.startswith("TIER_") for k in final), \
        f"TIER_* still raised after heal: {final}"
    st = svc.stats()
    return {"acked_writes": len(tier_model),
            "verified": len(tier_model),
            "workload": keys.describe(),
            "victim_pg": str(target_g.pgid),
            "victims": list(victims),
            "affected_objects": len(affected),
            "degraded_proxy_reads": degraded_proxies,
            "hit_rate": round(st["hit_rate"], 4),
            "counters": st["counters"]}


def run_campaign(seed: int = 7, ops: int = 40, data_dir=None,
                 verbose: bool = False) -> dict:
    """One full campaign; returns the report dict (raises AssertionError
    on any invariant violation)."""
    from ceph_tpu.backend import ecutil
    from ceph_tpu.backend.ecutil import StripeInfo
    from ceph_tpu.cluster import MiniCluster
    from ceph_tpu.failure import (FaultConfig, FaultPlan, StoreFaults,
                                  TransportFaults)
    from ceph_tpu.net import ClusterServer, TcpRados
    from ceph_tpu.ops.pipeline import CodecPipeline
    from ceph_tpu.plugins.registry import ErasureCodePluginRegistry

    def say(msg):
        if verbose:
            print(f"[chaos seed={seed}] {msg}", flush=True)

    own_dir = None
    if data_dir is None:
        own_dir = tempfile.mkdtemp(prefix="chaos_run_")
        data_dir = own_dir
    cct = _campaign_context()
    cluster = MiniCluster(n_osds=9, osds_per_host=3, chunk_size=CHUNK,
                          cct=cct, data_dir=data_dir)
    cluster.enable_recovery_scheduler()
    plan = FaultPlan(
        seed=seed,
        bus=FaultConfig(reorder=True, dup_prob=0.15),
        transport=TransportFaults(reset_prob=0.04, blackhole_prob=0.03,
                                  truncate_prob=0.02, delay_prob=0.10,
                                  delay_ms=2.0),
        store=StoreFaults(slow_read_prob=0.05, slow_read_ms=1.0))
    inj = cluster.inject_faults(plan)
    server = ClusterServer(cluster)
    server.inject_faults(inj)
    server.start()
    mon = cluster.attach_monitor()
    health_seen: set[str] = set()
    report: dict = {"seed": seed, "ops": ops}
    client = None
    try:
        client = TcpRados("127.0.0.1", server.port,
                          Path(data_dir) / "client.admin.keyring", cct=cct)
        client.mkpool("chaos", profile=dict(PROFILE), pg_num=4)
        pid = cluster.pool_ids["chaos"]

        # -- phase 1: acked writes + reads under transport+bus+store chaos
        say("phase 1: faulted traffic")
        rng = random.Random(f"workload:{seed}")
        model: dict[str, bytes] = {}
        for i in range(ops):
            oid = f"obj{i % max(1, ops // 2)}"
            data = rng.randbytes(2 * STRIPE)
            client.put("chaos", oid, data)      # acked == durable
            model[oid] = data
            if i % 5 == 4:
                check = sorted(model)[rng.randrange(len(model))]
                got = client.get("chaos", check)
                assert got == model[check], \
                    f"read of acked {check} diverged under injection"
        health_seen |= _health_checks(cluster)

        # -- phase 1.5: critical-path + SLO receipts for the window
        # above: retry time appeared (resent RPCs), the impossible
        # objective burned, and the burn CLEARS once traffic drains
        # past the burn windows — with the transitions in the clog
        say("phase 1.5: SLO burn + retry attribution")
        cluster.critpath.refresh()
        snap = cluster.critpath.snapshot()
        retry_s = sum(acc.get("retry", 0.0)
                      for acc in snap["phase_seconds"].values())
        # resends only: a reconnect healed during a call's FIRST attempt
        # stamps no net.resend span (that backoff lands in the rpc
        # span's self time), so reconnects alone guarantee nothing
        if client.resends:
            assert retry_s > 0, \
                f"{client.resends} resends but zero retry phase time " \
                f"attributed: {snap['phase_seconds']}"
        checks = _health_checks(cluster)
        health_seen |= checks
        assert "SLO_BURN" in checks or "SLO_EXHAUSTED" in checks, \
            f"impossible objective did not burn: {checks}"
        time.sleep(4.2)                      # drain past the slow window
        checks = _health_checks(cluster)
        assert "SLO_BURN" not in checks and \
            "SLO_EXHAUSTED" not in checks, \
            f"SLO burn did not clear after heal: {checks}"
        log_lines = [e["message"] for e in cluster.clusterlog.dump()]
        assert any("SLO_" in ln and "raised" in ln
                   for ln in log_lines), "no SLO raise in clusterlog"
        assert any("SLO_" in ln and "cleared" in ln
                   for ln in log_lines), "no SLO clear in clusterlog"
        report["slo"] = {
            "retry_phase_s": round(retry_s, 6),
            "traces_folded": cluster.critpath.folded,
            "classes": {cls: {"retry_s": round(acc.get("retry", 0), 6)}
                        for cls, acc in snap["phase_seconds"].items()},
        }

        # -- phase 2: flapping OSD -> damping -> operator clear
        say("phase 2: flapping OSD")
        primaries = {g.backend.whoami
                     for g in cluster.pools[pid]["pgs"].values()}
        victim = min(set(range(9)) - primaries - {0})
        hosts = {o: o // 3 for o in range(9)}
        reporters = [o for o in range(9)
                     if hosts[o] != hosts[victim] and o != victim]
        rep_a = reporters[0]
        rep_b = next(o for o in reporters if hosts[o] != hosts[rep_a])
        now, denied_at = 100.0, None
        for cycle in range(5):
            now += 30.0
            mon.prepare_failure(victim, rep_a, failed_since=now - 25.0,
                                now=now)
            mon.prepare_failure(victim, rep_b, failed_since=now - 25.0,
                                now=now)
            mon.propose_pending(now)
            assert cluster.osdmap.is_down(victim), \
                f"flap cycle {cycle}: victim not marked down"
            health_seen |= _health_checks(cluster)
            booted = mon.osd_boot(victim, now=now + 1.0)
            mon.propose_pending(now + 1.0)
            if not booted:
                denied_at = cycle
                break
        assert denied_at is not None, "flap damping never tripped"
        assert cluster.osdmap.is_down(victim)
        checks = _health_checks(cluster)
        health_seen |= checks
        assert "OSD_FLAPPING" in checks, \
            f"OSD_FLAPPING not raised: {checks}"
        mon.clear_markdown(victim)
        assert mon.osd_boot(victim, now=now + 2.0)
        mon.propose_pending(now + 2.0)
        assert cluster.osdmap.is_up(victim)
        assert "OSD_FLAPPING" not in _health_checks(cluster), \
            "OSD_FLAPPING did not clear after operator clear + boot"
        report["flap"] = {"victim": victim, "denied_at_cycle": denied_at}

        # -- phase 3: device breaker -> host fallback -> probe re-close
        say("phase 3: device breaker")
        ec_dev = ErasureCodePluginRegistry.instance().factory(
            "jax_rs", "", {**PROFILE, "device": "jax"})
        sinfo = StripeInfo(K, CHUNK)
        pipeline = CodecPipeline(depth=2, name=f"chaos{seed}.pipeline",
                                 cct=cct)
        try:
            pipeline.inject_faults(inj)
            plan.device.dispatch_fail_prob = 1.0
            bufs = [rng.randbytes(2 * STRIPE) for _ in range(6)]
            futs = [ecutil.encode_many_pipelined(sinfo, ec_dev, [b],
                                                 pipeline)
                    for b in bufs]
            pipeline.flush()
            for buf, fut in zip(bufs, futs):
                got = fut.result(30)[0]
                want = ecutil.encode(sinfo, ec_dev, buf)
                assert {c: bytes(v) for c, v in got.items()} == \
                    {c: bytes(v) for c, v in want.items()}, \
                    "host-fallback parity diverged from sync encode"
            assert pipeline.breaker.state == "open", \
                f"breaker did not open: {pipeline.breaker.dump()}"
            checks = _health_checks(cluster)
            health_seen |= checks
            assert "DEVICE_DEGRADED" in checks, \
                f"DEVICE_DEGRADED not raised: {checks}"
            # injection off; after the cooldown the next submit probes
            plan.device.dispatch_fail_prob = 0.0
            time.sleep(0.06)
            probe = ecutil.encode_many_pipelined(sinfo, ec_dev,
                                                 [bufs[0]], pipeline)
            pipeline.flush()
            probe.result(30)
            assert pipeline.breaker.state == "closed", \
                f"half-open probe did not re-close: " \
                f"{pipeline.breaker.dump()}"
            assert "DEVICE_DEGRADED" not in _health_checks(cluster), \
                "DEVICE_DEGRADED did not clear after the breaker closed"
            report["breaker"] = pipeline.breaker.dump()
        finally:
            pipeline.close()

        # -- phase 4: drain + verify every acked write, both surfaces
        say("phase 4: drain + verify")
        for _ in range(20):
            cluster.deliver_all()
            if cluster.recovery.job_counts() == (0, 0):
                break
        assert cluster.recovery.job_counts() == (0, 0), \
            f"recovery reservations not drained: " \
            f"{cluster.recovery.job_counts()}"
        for oid, want in sorted(model.items()):
            assert client.get("chaos", oid) == want, \
                f"acked write {oid} lost (TCP read)"
            assert cluster.get(pid, oid, len(want)) == want, \
                f"acked write {oid} lost (local read)"

        # -- phase 5: cache tier flash crowd + tier OSD death
        say("phase 5: cache tier flash crowd + tier OSD death")
        report["tier"] = _tier_phase(cluster, mon, cct, pid, seed, ops,
                                     rng, now, health_seen, say)

        report.update({
            "ok": True,
            "acked_writes": len(model),
            "verified": len(model),
            "events": inj.summary(),
            "event_digest": inj.event_digest(),
            "transport": {"reconnects": client.reconnects,
                          "resends": client.resends,
                          "rpc_dedup_hits": server.rpc_dedup_hits},
            "health_seen": sorted(health_seen),
        })
        say(f"done: {report['events']['total']} events, digest "
            f"{report['event_digest'][:12]}")
        return report
    finally:
        if client is not None:
            client.close()
        server.stop()
        cluster.shutdown()
        if own_dir is not None:
            shutil.rmtree(own_dir, ignore_errors=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--ops", type=int, default=40,
                    help="client writes in the faulted-traffic phase")
    ap.add_argument("--data-dir", default=None,
                    help="durable cluster home (default: a temp dir)")
    ap.add_argument("--json", default=None,
                    help="write the report to this file")
    ap.add_argument("-q", "--quiet", action="store_true")
    args = ap.parse_args(argv)
    try:
        report = run_campaign(seed=args.seed, ops=args.ops,
                              data_dir=args.data_dir,
                              verbose=not args.quiet)
    except AssertionError as e:
        print(f"CHAOS FAIL: {e}", file=sys.stderr)
        return 1
    out = json.dumps(report, indent=2, default=str)
    if args.json:
        Path(args.json).write_text(out + "\n")
    print(out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
