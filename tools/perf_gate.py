#!/usr/bin/env python3
"""Perf-regression gate: diff a fresh bench artifact against the record.

The roadmap's open wound: BENCH_r04 errored, BENCH_r05 silently fell
back to CPU and recorded 7.5 GiB/s as if it were a kernel regression.
This gate makes both failure modes LOUD and machine-checkable:

- **regression**: each comparable block (the core codec metric plus the
  ``serving``/``recovery``/``pipeline`` blocks) is diffed against the
  reference artifact with a per-metric threshold; a drop past the
  threshold fails the gate;
- **platform fallback**: an artifact whose device degraded below the
  expected platform (expected TPU, measured CPU — the r05 failure mode)
  is a hard FAIL no matter how healthy its numbers look; a CPU number is
  not a slower TPU number, it is a different experiment;
- **verdict**: one line on stdout (``PERF GATE: PASS ...`` /
  ``PERF GATE: FAIL ...``) and exit 0/1, suitable for CI and for the
  driver's BENCH_r capture.

Inputs are bench.py's one-line JSON artifact, or a driver BENCH_r*.json
wrapper (its ``parsed`` field), or BASELINE_RESULTS.json-style documents
— :func:`extract_metrics` normalizes all three.  ``bench.py`` calls
:func:`evaluate` in-process and stamps the verdict into every artifact
it emits (the ``gate`` field), so every future BENCH_r*.json lands with
its own gate verdict attached.

Stdlib-only, standalone on purpose (tools/trace_report.py's discipline).
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

# metric id -> (artifact path, higher-is-better).  Paths resolve inside
# the normalized bench line; missing paths simply don't participate.
METRIC_PATHS = {
    "core.mib_s": (("value",), True),
    "serving.ops_s": (("serving", "batched", "ops_s"), True),
    "serving.p99_ms": (("serving", "batched", "p99_ms"), False),
    "recovery.mib_s": (("recovery", "batched", "mib_s"), True),
    "pipeline.mib_s": (("pipeline", "async", "mib_s"), True),
    # wire efficiency (ISSUE 7): bytes-on-wire per byte repaired / per
    # served op — lower is better; a rise past threshold means repair or
    # serving started moving more network bytes for the same work
    "recovery.wire_per_byte": (("recovery", "wire", "per_byte_repaired"),
                               False),
    "serving.wire_per_op": (("serving", "wire", "per_op"), False),
    # device efficiency (ISSUE 8): aggregate %-of-roofline-peak from the
    # per-executable ledger — a drop means the kernels moved AWAY from
    # what the hardware allows even if raw throughput held (e.g. more
    # dispatches doing the same work)
    "efficiency.pct_of_peak": (("efficiency", "pct_of_peak"), True),
    # resilience (ISSUE 9): goodput under the fixed fault schedule as a
    # fraction of the clean run (self-healing tax — a drop means retry/
    # dedup/fallback machinery got more expensive), and the host-codec
    # throughput floor while the device breaker is open
    "resilience.goodput_ratio": (("resilience", "goodput_ratio"), True),
    "resilience.fallback_mib_s": (("resilience", "breaker",
                                   "fallback_mib_s"), True),
    # latency SLO (ISSUE 10): client-class p99 from the critical-path
    # ledger (lower is better), and the remaining error budget under
    # the bench objective — a budget-burn regression (drop) fails the
    # gate even when throughput held
    "slo.client_p99_ms": (("slo", "client", "p99_ms"), False),
    "slo.budget_remaining": (("slo", "client", "budget_remaining"),
                             True),
    # chained streaming repair (ISSUE 12): throughput and the wire
    # decomposition of the chain arm, diffed against the reference like
    # every other block AND capped absolutely (METRIC_LIMITS below) so
    # the bandwidth-optimality claims can't silently erode
    "recovery.chain.mib_s": (("recovery", "chain", "mib_s"), True),
    "recovery.chain.wire_per_byte": (
        ("recovery", "chain", "wire_per_byte"), False),
    "recovery.chain.coordinator_ingress_per_byte": (
        ("recovery", "chain", "coordinator_ingress_per_byte"), False),
    "recovery.chain.newcomer_ingress_per_byte": (
        ("recovery", "chain", "newcomer_ingress_per_byte"), False),
    "recovery.chain.speedup_vs_centralized": (
        ("recovery", "chain", "speedup_vs_centralized"), True),
    # regenerating-code repair (ISSUE 17): total recovery wire per
    # stored byte repaired on a pm_regen pool, diffed like the rest AND
    # capped absolutely (METRIC_LIMITS) — MBR claims ~1.0 B/B, under
    # every decode-based repair's k-transfer floor; MSR claims d/alpha
    "recovery.regen.mbr.mib_s": (
        ("recovery", "regen", "mbr", "mib_s"), True),
    "recovery.regen.mbr.wire_per_byte": (
        ("recovery", "regen", "mbr", "wire_per_byte"), False),
    "recovery.regen.mbr.wire_reduction": (
        ("recovery", "regen", "mbr", "wire_reduction"), True),
    "recovery.regen.msr.wire_per_byte": (
        ("recovery", "regen", "msr", "wire_per_byte"), False),
    # async messenger (ISSUE 14): 10k logical closed-loop clients over
    # few connections — clean-capacity goodput and p99, plus goodput
    # while the overload arm sheds by class.  `clients` is held to an
    # absolute floor (METRIC_LIMITS): the concurrency claim itself.
    "serving.async.ops_s": (("serving", "async", "ops_s"), True),
    "serving.async.p99_ms": (("serving", "async", "p99_ms"), False),
    "serving.async.clients": (("serving", "async", "clients"), True),
    "serving.async.overload.ops_s": (
        ("serving", "async", "overload", "ops_s"), True),
    # zero-copy data path (ISSUE 20): the fused socket->HBM arms over
    # the legacy pickle path at bulk payload size.  copies_per_byte is
    # the claim itself — held to an absolute cap (METRIC_LIMITS), with
    # the legacy arm's ratio held to an absolute FLOOR so the contrast
    # the cap is measured against cannot quietly erode (a "legacy" arm
    # that stops copying is a broken bench, not a better baseline).
    "serving.zero_copy.copies_per_byte": (
        ("serving", "zero_copy", "copies_per_byte"), False),
    "serving.zero_copy.legacy_copies_per_byte": (
        ("serving", "zero_copy", "legacy_copies_per_byte"), True),
    "serving.zero_copy.ops_s": (
        ("serving", "zero_copy", "fused", "ops_s"), True),
    "serving.zero_copy.p99_ms": (
        ("serving", "zero_copy", "fused", "p99_ms"), False),
    "serving.zero_copy.goodput_ratio": (
        ("serving", "zero_copy", "goodput_ratio"), True),
    # static analysis (ISSUE 15): the ceph-lint trajectory. `new` is
    # held to an absolute zero (METRIC_LIMITS) — any non-baselined
    # finding fails the round; `baselined` is diffed against the
    # reference so suppressed debt can't quietly snowball.
    "lint.new": (("lint", "new"), False),
    "lint.baselined": (("lint", "baselined"), False),
    # observability fast path (ISSUE 18): the instrumentation tax over
    # the serving.async mux workload — instruments-on goodput diffed
    # like every throughput metric, and overhead_pct held to an
    # ABSOLUTE cap (METRIC_LIMITS): full instruments at default
    # sampling must cost single-digit percent, every artifact, no ref
    "observability.overhead_pct": (("observability", "overhead_pct"),
                                   False),
    "observability.ops_s": (
        ("observability", "instruments_on", "ops_s"), True),
    # cache tiering (ROADMAP 7): a warm writeback tier under the
    # flash-crowd mux workload must ABSORB the crowd — hit rate held to
    # an absolute floor, warm-over-cold p99 and device-time ratios to
    # absolute caps (METRIC_LIMITS): a tier that is slower than the EC
    # base it fronts, or that pays EC encode for absorbed writes, is a
    # regression in the subsystem's whole reason to exist
    "tiering.hit_rate": (("tiering", "warm", "hit_rate"), True),
    "tiering.warm_p99_ms": (("tiering", "warm", "p99_ms"), False),
    "tiering.cold_p99_ms": (("tiering", "cold", "p99_ms"), False),
    "tiering.warm_over_cold_p99": (("tiering", "warm_over_cold_p99"),
                                   False),
    "tiering.warm_over_cold_device_us": (
        ("tiering", "warm_over_cold_device_us"), False),
    "tiering.warm_promotions": (("tiering", "warm", "promotions"),
                                False),
}

# absolute bounds checked on the NEW artifact alone — no reference
# needed, so a first-ever chain artifact is still held to the claims.
# ("max": value must stay at or below; "min": at or above.)  Total
# chain wire cannot beat the k-transfer information floor (~k per
# repaired byte at k=4 with one erasure); what IS gated hard is that
# the newcomer receives ~1x the bytes it re-hosts (<= 1.5, the ISSUE 12
# criterion), the coordinator stays out of the data path, total wire
# keeps beating the centralized arm's ~6x, and the chain arm is not
# slower than the centralized wave it replaces (0.95 absorbs timer
# jitter between the two back-to-back passes).
METRIC_LIMITS = {
    "recovery.chain.newcomer_ingress_per_byte": (1.5, "max"),
    # the ISSUE 17 criteria: MBR total wire at or under 1.5x the stored
    # bytes repaired (the ~1 B/B claim with control-leg headroom), and
    # any regenerating pool under the 4.0 ceiling
    "recovery.regen.mbr.wire_per_byte": (1.5, "max"),
    "recovery.regen.msr.wire_per_byte": (4.0, "max"),
    "recovery.chain.coordinator_ingress_per_byte": (0.5, "max"),
    "recovery.chain.wire_per_byte": (4.6, "max"),
    "recovery.chain.speedup_vs_centralized": (0.95, "min"),
    # the ISSUE 14 acceptance floor: the async bench must actually run
    # >= 10k concurrent closed-loop sessions, every artifact, no ref
    "serving.async.clients": (10000, "min"),
    # the ISSUE 20 acceptance caps: the fused arm moves each served
    # payload byte at most ~1.3 times end to end (staging + client
    # materialize + compaction tail), while the legacy pickle arm's
    # >= 3 copies/byte keeps the contrast honest; the fused arm must
    # also not LOSE goodput to the copies it saved (1.0 floor with the
    # wall-clock jitter absorbed by the diff threshold below)
    "serving.zero_copy.copies_per_byte": (1.3, "max"),
    "serving.zero_copy.legacy_copies_per_byte": (3.0, "min"),
    "serving.zero_copy.goodput_ratio": (1.0, "min"),
    # ceph-lint must run clean against the committed baseline in every
    # artifact — a new finding is a bug (or a missing justification),
    # never acceptable drift
    "lint.new": (0, "max"),
    # the ISSUE 18 acceptance cap: full instruments at default sampling
    # cost <= 10% of kill-switch goodput on the mux workload (to be
    # ratcheted down as the fast path matures)
    "observability.overhead_pct": (10.0, "max"),
    # the tiering acceptance criteria: the warm pass over the identical
    # flash-crowd stream hits >= 0.8, is no slower than the cold EC
    # pass at p99, and spends STRICTLY less device time per op (the
    # write encodes writeback absorption elides; the ratio key is only
    # emitted when the cold arm's device time is measurable).  A warmed
    # tier also must not churn promotions: the warmup pass earned
    # residency, the warm pass should mostly find it.
    "tiering.hit_rate": (0.8, "min"),
    "tiering.warm_over_cold_p99": (1.0, "max"),
    "tiering.warm_over_cold_device_us": (0.99, "max"),
    "tiering.warm_promotions": (100, "max"),
}

# fraction of regression tolerated per metric before the gate fails;
# latency metrics (higher-is-worse) use the same fraction as an allowed
# increase.  Overridable per metric via --threshold NAME=0.15.
DEFAULT_THRESHOLD = 0.10

# per-metric defaults that differ from DEFAULT_THRESHOLD: the %-of-peak
# join divides modeled work by dispatch WALL seconds, which on a shared
# cpu host is the noisiest number the gate carries — gate it loosely so
# only a real efficiency cliff (not scheduler jitter) fails the round
METRIC_THRESHOLDS = {"efficiency.pct_of_peak": 0.30,
                     # both resilience numbers divide two wall-clock
                     # measurements on a possibly-shared host: gate only
                     # real cliffs, not scheduler jitter
                     "resilience.goodput_ratio": 0.30,
                     "resilience.fallback_mib_s": 0.30,
                     # per-op p99 on a shared host is tail-of-the-tail
                     # noisy; budget_remaining compounds that through a
                     # threshold — gate only real cliffs
                     "slo.client_p99_ms": 0.50,
                     "slo.budget_remaining": 0.30,
                     # a ratio of two wall-clock arms: gate cliffs only
                     # (the absolute floor in METRIC_LIMITS still holds)
                     "recovery.chain.speedup_vs_centralized": 0.30,
                     # wall-clock repair throughput and an arm ratio on
                     # a possibly-shared host: gate cliffs only (the
                     # wire caps above carry the real claims)
                     "recovery.regen.mbr.mib_s": 0.30,
                     "recovery.regen.mbr.wire_reduction": 0.30,
                     # socket wall-clock at 10k concurrency on a shared
                     # host: gate cliffs, not scheduler jitter
                     "serving.async.ops_s": 0.30,
                     "serving.async.p99_ms": 0.50,
                     "serving.async.overload.ops_s": 0.30,
                     # two closed-loop socket arms on a shared host:
                     # the wall-clock numbers gate cliffs only — the
                     # copy ratios are deterministic byte counts and
                     # keep the default tight diff
                     "serving.zero_copy.ops_s": 0.30,
                     "serving.zero_copy.p99_ms": 0.50,
                     "serving.zero_copy.goodput_ratio": 0.30,
                     # a small integer count: one justified baseline
                     # entry is ~6% at today's size, so diff loosely and
                     # let review argue each justification — the gate
                     # only stops a silent suppression avalanche
                     "lint.baselined": 0.50,
                     # a ratio of two back-to-back wall-clock socket
                     # arms: the absolute 10% cap in METRIC_LIMITS is
                     # the real gate; the diff only stops a cliff
                     "observability.overhead_pct": 5.0,
                     "observability.ops_s": 0.30,
                     # closed-loop p99 at mux-client scale on a shared
                     # host is tail-of-the-tail noisy; the absolute
                     # caps above carry the real tiering claims — the
                     # diffs only stop cliffs
                     "tiering.hit_rate": 0.15,
                     "tiering.warm_p99_ms": 0.50,
                     "tiering.cold_p99_ms": 0.50,
                     "tiering.warm_over_cold_p99": 0.30,
                     "tiering.warm_over_cold_device_us": 0.50,
                     "tiering.warm_promotions": 1.0}

_BLOCK_DEVICE = {
    "core.mib_s": ("device",),
    "serving.ops_s": ("serving", "device"),
    "serving.p99_ms": ("serving", "device"),
    "recovery.mib_s": ("recovery", "device"),
    "pipeline.mib_s": ("pipeline", "device"),
    "recovery.wire_per_byte": ("recovery", "device"),
    "serving.wire_per_op": ("serving", "device"),
    "efficiency.pct_of_peak": ("efficiency", "device"),
    "resilience.goodput_ratio": ("resilience", "device"),
    "resilience.fallback_mib_s": ("resilience", "device"),
    "slo.client_p99_ms": ("slo", "device"),
    "slo.budget_remaining": ("slo", "device"),
    "recovery.chain.mib_s": ("recovery", "device"),
    "recovery.chain.wire_per_byte": ("recovery", "device"),
    "recovery.chain.coordinator_ingress_per_byte": ("recovery", "device"),
    "recovery.chain.newcomer_ingress_per_byte": ("recovery", "device"),
    "recovery.chain.speedup_vs_centralized": ("recovery", "device"),
    "recovery.regen.mbr.mib_s": ("recovery", "device"),
    "recovery.regen.mbr.wire_per_byte": ("recovery", "device"),
    "recovery.regen.mbr.wire_reduction": ("recovery", "device"),
    "recovery.regen.msr.wire_per_byte": ("recovery", "device"),
    "serving.async.ops_s": ("serving", "device"),
    "serving.async.p99_ms": ("serving", "device"),
    "serving.async.clients": ("serving", "device"),
    "serving.async.overload.ops_s": ("serving", "device"),
    "serving.zero_copy.copies_per_byte": ("serving", "device"),
    "serving.zero_copy.legacy_copies_per_byte": ("serving", "device"),
    "serving.zero_copy.ops_s": ("serving", "device"),
    "serving.zero_copy.p99_ms": ("serving", "device"),
    "serving.zero_copy.goodput_ratio": ("serving", "device"),
    # lint is host-side AST work; the block carries no device marker, so
    # these fall back to the artifact's overall platform
    "lint.new": ("lint", "device"),
    "lint.baselined": ("lint", "device"),
    "observability.overhead_pct": ("observability", "device"),
    "observability.ops_s": ("observability", "device"),
    "tiering.hit_rate": ("tiering", "device"),
    "tiering.warm_p99_ms": ("tiering", "device"),
    "tiering.cold_p99_ms": ("tiering", "device"),
    "tiering.warm_over_cold_p99": ("tiering", "device"),
    "tiering.warm_over_cold_device_us": ("tiering", "device"),
    "tiering.warm_promotions": ("tiering", "device"),
}


def _dig(doc: dict, path: tuple):
    cur = doc
    for key in path:
        if not isinstance(cur, dict) or key not in cur:
            return None
        cur = cur[key]
    return cur


def normalize(doc: dict) -> dict:
    """Accept a bare bench line, or a driver BENCH_r wrapper (use its
    ``parsed``), and return the bench-line dict."""
    if not isinstance(doc, dict):
        raise ValueError("artifact is not a JSON object")
    if "parsed" in doc and isinstance(doc["parsed"], dict):
        return doc["parsed"]
    return doc


def extract_metrics(doc: dict) -> dict[str, dict]:
    """{metric id: {value, device, higher_better}} for every comparable
    number present in the artifact."""
    line = normalize(doc)
    # legacy-shape lines (pre-r04) carry no device markers at all:
    # artifact_platform's inference fills in, so a TPU record still
    # participates in per-metric comparison instead of being skipped as
    # device-unknown
    default_device = artifact_platform(doc)
    out: dict[str, dict] = {}
    for mid, (path, higher) in METRIC_PATHS.items():
        v = _dig(line, path)
        if not isinstance(v, (int, float)):
            continue
        device = _dig(line, _BLOCK_DEVICE[mid]) or default_device
        out[mid] = {"value": float(v), "device": device,
                    "higher_better": higher}
    return out


def artifact_platform(doc: dict) -> str | None:
    """The platform the artifact's core number was measured on."""
    line = normalize(doc)
    dev = line.get("device")
    if dev is None:
        dev = _dig(line, ("device_info", "platform"))
    if dev is None and "error" not in line and "value" in line:
        # pre-r04 artifact shape: only TPU successes omitted both the
        # device marker and the error field (BENCH_r03's record line)
        dev = "tpu"
    return dev


def evaluate(new: dict, reference: dict | None,
             thresholds: dict[str, float] | None = None,
             expect_platform: str | None = None) -> dict:
    """Gate one artifact.  Returns ``{ok, verdict, failures, compared}``;
    ``verdict`` is the one-line summary.  ``reference=None`` checks only
    the platform expectation (first run: nothing to diff against)."""
    thresholds = thresholds or {}
    failures: list[str] = []
    compared: list[dict] = []

    new_platform = artifact_platform(new)
    if expect_platform and new_platform != expect_platform:
        # the r05 failure mode: a degraded-platform artifact must be an
        # ERROR, not a silently lower number
        failures.append(
            f"platform fallback: expected {expect_platform}, measured "
            f"{new_platform or 'none'}")

    new_metrics = extract_metrics(new)
    ref_metrics = extract_metrics(reference) if reference else {}
    for mid, ref in sorted(ref_metrics.items()):
        cur = new_metrics.get(mid)
        if cur is None:
            failures.append(f"{mid}: present in reference, missing in "
                            f"new artifact")
            continue
        if ref["device"] != cur["device"]:
            if ref["device"] == "tpu" and cur["device"] in ("cpu", None):
                failures.append(
                    f"{mid}: platform fallback (reference on tpu, new on "
                    f"{cur['device'] or 'none'})")
            # cpu-vs-tpu numbers are different experiments: never diffed
            continue
        thr = thresholds.get(
            mid, thresholds.get(
                "*", METRIC_THRESHOLDS.get(mid, DEFAULT_THRESHOLD)))
        if ref["value"] <= 0:
            continue
        ratio = cur["value"] / ref["value"]
        comp = {"metric": mid, "new": cur["value"], "ref": ref["value"],
                "ratio": round(ratio, 3), "device": cur["device"],
                "threshold": thr}
        compared.append(comp)
        if cur["higher_better"] and ratio < 1.0 - thr:
            failures.append(
                f"{mid}: {cur['value']:.1f} vs {ref['value']:.1f} "
                f"({100 * (1 - ratio):.0f}% regression > {100 * thr:.0f}% "
                f"threshold, {cur['device']})")
        elif not cur["higher_better"] and ratio > 1.0 + thr:
            failures.append(
                f"{mid}: {cur['value']:.2f} vs {ref['value']:.2f} "
                f"({100 * (ratio - 1):.0f}% increase > {100 * thr:.0f}% "
                f"threshold, {cur['device']})")

    for mid, (bound, kind) in sorted(METRIC_LIMITS.items()):
        cur = new_metrics.get(mid)
        if cur is None:
            continue
        v = cur["value"]
        if kind == "max" and v > bound:
            failures.append(
                f"{mid}: {v:.2f} exceeds absolute cap {bound}")
        elif kind == "min" and v < bound:
            failures.append(
                f"{mid}: {v:.2f} below absolute floor {bound}")

    ok = not failures
    if ok:
        detail = (f"{len(compared)} metrics within thresholds"
                  if compared else "no comparable reference metrics")
        verdict = (f"PERF GATE: PASS ({detail}; platform="
                   f"{new_platform or 'none'})")
    else:
        verdict = f"PERF GATE: FAIL ({'; '.join(failures)})"
    return {"ok": ok, "verdict": verdict, "failures": failures,
            "compared": compared}


def scan_history(repo_dir: str
                 ) -> tuple[dict | None, str | None, str | None]:
    """ONE pass over the BENCH_r*.json history (bench.py runs this per
    emitted artifact): returns ``(reference_doc, reference_path,
    expected_platform)``.

    The reference is the newest HEALTHY round with a parsed bench line.
    Errored/fallback artifacts (the parsed line carries an ``error``
    field — r04/r05's shape) are skipped while any clean round exists:
    the degraded artifact the gate exists to catch must never become
    the baseline it measures against.  When every round errored, the
    newest one still serves (cpu-only histories compare cpu-vs-cpu
    legitimately).

    The expected platform is 'tpu' when ANY round measured on tpu —
    once the record is a device number, a cpu artifact is a fallback,
    not a baseline."""
    best: tuple[int, dict, str] | None = None
    best_clean: tuple[int, dict, str] | None = None
    expect: str | None = None
    for path in glob.glob(os.path.join(repo_dir, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        parsed = doc.get("parsed")
        if not isinstance(parsed, dict):
            continue
        if expect is None and artifact_platform(doc) == "tpu":
            expect = "tpu"
        n = int(m.group(1))
        if best is None or n > best[0]:
            best = (n, doc, path)
        if "error" not in parsed and \
                (best_clean is None or n > best_clean[0]):
            best_clean = (n, doc, path)
    best = best_clean or best
    if best is None:
        return None, None, expect
    return best[1], best[2], expect


def find_reference(repo_dir: str) -> tuple[dict | None, str | None]:
    """The newest healthy BENCH_r*.json (see :func:`scan_history`)."""
    doc, path, _expect = scan_history(repo_dir)
    return doc, path


def expected_platform(repo_dir: str) -> str | None:
    """'tpu' when any prior round measured on tpu (see
    :func:`scan_history`)."""
    return scan_history(repo_dir)[2]


def gate_for_bench(line: dict, repo_dir: str) -> dict:
    """The in-process entry bench.py uses: reference + expected platform
    discovered from the repo's BENCH history, verdict attached to the
    artifact.  Never raises (the artifact must always emit)."""
    reference, ref_path, expect = scan_history(repo_dir)
    res = evaluate(line, reference, expect_platform=expect)
    res["reference"] = os.path.basename(ref_path) if ref_path else None
    res["expected_platform"] = expect
    return res


def _parse_thresholds(entries: list[str]) -> dict[str, float]:
    out: dict[str, float] = {}
    for e in entries:
        name, _, val = e.partition("=")
        if not val:
            out["*"] = float(name)
        else:
            out[name] = float(val)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="diff a bench artifact against the recorded "
                    "reference; exit nonzero on regression or platform "
                    "fallback")
    ap.add_argument("artifact", help="fresh bench JSON (bench.py line or "
                                     "BENCH_r wrapper)")
    ap.add_argument("--baseline",
                    help="explicit reference artifact (default: newest "
                         "BENCH_r*.json next to --repo-dir)")
    ap.add_argument("--repo-dir", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))),
        help="where BENCH_r*.json history lives")
    ap.add_argument("--expect-platform",
                    help="hard-fail unless the artifact measured on this "
                         "platform (default: tpu when any prior round "
                         "did)")
    ap.add_argument("--threshold", action="append", default=[],
                    metavar="[METRIC=]FRACTION",
                    help="per-metric regression tolerance (bare number "
                         "sets the default for all metrics)")
    ap.add_argument("--check", action="store_true",
                    help="CI mode: print ONLY the one-line verdict")
    ap.add_argument("--json", action="store_true",
                    help="emit the full evaluation as JSON")
    args = ap.parse_args(argv)

    with open(args.artifact) as f:
        new = json.load(f)
    reference, ref_name, history_expect = scan_history(args.repo_dir)
    if args.baseline:
        with open(args.baseline) as f:
            reference = json.load(f)
        ref_name = args.baseline
    expect = args.expect_platform
    if expect is None:
        expect = history_expect

    res = evaluate(new, reference, _parse_thresholds(args.threshold),
                   expect_platform=expect)
    if args.json:
        res["reference"] = ref_name
        res["expected_platform"] = expect
        print(json.dumps(res))
    elif args.check:
        print(res["verdict"])
    else:
        for c in res["compared"]:
            print(f"  {c['metric']:<18} {c['new']:>12.2f} vs "
                  f"{c['ref']:>12.2f}  x{c['ratio']:.3f}  "
                  f"[{c['device']}]")
        print(res["verdict"])
    return 0 if res["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
