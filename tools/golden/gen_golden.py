#!/usr/bin/env python3
"""Regenerate tests/golden/crush_golden.json from the reference C core.

Requires /root/reference to be mounted (dev environment only); the committed
JSON is what CI/tests consume, so this only needs re-running when the golden
scenario set in golden_gen.c changes.
"""
import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(os.path.dirname(HERE))
REF = os.environ.get("CEPH_REFERENCE", "/root/reference")
OUT = os.path.join(REPO, "tests", "golden", "crush_golden.json")


def main() -> int:
    if not os.path.isdir(os.path.join(REF, "src", "crush")):
        print(f"reference not found at {REF}; cannot regenerate", file=sys.stderr)
        return 1
    with open(os.path.join(HERE, "acconfig.h"), "w") as f:
        f.write("#define HAVE_LINUX_TYPES_H 1\n")
    exe = os.path.join(HERE, "golden_gen")
    subprocess.check_call([
        "gcc", "-O1", "-I", HERE,
        "-I", os.path.join(REF, "src", "crush"),
        "-I", os.path.join(REF, "src"),
        "-o", exe,
        os.path.join(HERE, "golden_gen.c"),
        os.path.join(HERE, "golden_mapper.c"),
        "-lm",
    ])
    # full-domain crush_ln LUT (the straw2 draw domain) as packaged data
    lut = subprocess.check_output([exe, "lntable"]).decode().split()
    import numpy as np
    arr = np.array([int(v) for v in lut], dtype=np.uint64)
    assert arr.shape == (65536,)
    data_dir = os.path.join(REPO, "ceph_tpu", "crush", "data")
    os.makedirs(data_dir, exist_ok=True)
    np.save(os.path.join(data_dir, "crush_ln16.npy"), arr)
    print(f"wrote crush_ln16.npy ({arr.nbytes} bytes)")

    raw = subprocess.check_output([exe]).decode()
    data = json.loads(raw)  # validate before writing
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    with open(OUT, "w") as f:
        json.dump(data, f, separators=(",", ":"))
        f.write("\n")
    ngroups = len(data["groups"])
    nruns = sum(len(g["runs"]) for g in data["groups"])
    print(f"wrote {OUT}: {ngroups} map groups, {nruns} runs")
    return 0


if __name__ == "__main__":
    sys.exit(main())
