/* Golden-vector generator for the CRUSH reimplementation.
 *
 * Compiles the *reference* CRUSH C core (hash.c, crush.c, builder.c,
 * mapper.c under /root/reference/src/crush) by #include-by-path — nothing is
 * copied into this repository — builds a set of test maps through the
 * public builder API, runs crush_do_rule() / crush_hash32*() / crush_ln()
 * on them, and emits JSON golden vectors (including full map dumps) on
 * stdout.  tests/golden/crush_golden.json is the committed output; tests
 * compare the JAX/numpy reimplementation bit-for-bit against it
 * (SURVEY.md §7: CRUSH requires exact uint32 overflow semantics).
 *
 * Build + regenerate: python tools/golden/gen_golden.py
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "hash.c"
#include "crush.c"
#include "builder.c"
#include "mapper.h"
extern unsigned long long golden_crush_ln(unsigned int x);

static void emit_hash_golden(void) {
    unsigned int xs[] = {0u, 1u, 2u, 12345u, 0x12345678u, 0xffffffffu,
                         0xdeadbeefu, 4294967290u, 716740u, 42u};
    int n = sizeof(xs) / sizeof(xs[0]);
    printf("  \"hash\": {\n    \"inputs\": [");
    for (int i = 0; i < n; i++) printf("%s%u", i ? "," : "", xs[i]);
    printf("],\n    \"h1\": [");
    for (int i = 0; i < n; i++)
        printf("%s%u", i ? "," : "", crush_hash32(CRUSH_HASH_RJENKINS1, xs[i]));
    printf("],\n    \"h2\": [");
    for (int i = 0; i < n; i++)
        printf("%s%u", i ? "," : "",
               crush_hash32_2(CRUSH_HASH_RJENKINS1, xs[i], xs[(i + 1) % n]));
    printf("],\n    \"h3\": [");
    for (int i = 0; i < n; i++)
        printf("%s%u", i ? "," : "",
               crush_hash32_3(CRUSH_HASH_RJENKINS1, xs[i], xs[(i + 1) % n],
                              xs[(i + 2) % n]));
    printf("],\n    \"h4\": [");
    for (int i = 0; i < n; i++)
        printf("%s%u", i ? "," : "",
               crush_hash32_4(CRUSH_HASH_RJENKINS1, xs[i], xs[(i + 1) % n],
                              xs[(i + 2) % n], xs[(i + 3) % n]));
    printf("],\n    \"h5\": [");
    for (int i = 0; i < n; i++)
        printf("%s%u", i ? "," : "",
               crush_hash32_5(CRUSH_HASH_RJENKINS1, xs[i], xs[(i + 1) % n],
                              xs[(i + 2) % n], xs[(i + 3) % n], xs[(i + 4) % n]));
    printf("]\n  },\n");
}

static void emit_ln_golden(void) {
    printf("  \"crush_ln\": {\"inputs\": [");
    for (int i = 0; i <= 0xffff; i += 17)
        printf("%s%d", i ? "," : "", i);
    printf("],\n    \"values\": [");
    int first = 1;
    for (int i = 0; i <= 0xffff; i += 17) {
        printf("%s%llu", first ? "" : ",", golden_crush_ln((unsigned int)i));
        first = 0;
    }
    printf("]\n  },\n");
}

/* ---- map dump ---------------------------------------------------------- */

static void emit_u32s(const char *key, const __u32 *v, int n) {
    printf("\"%s\": [", key);
    for (int i = 0; i < n; i++) printf("%s%u", i ? "," : "", v[i]);
    printf("]");
}

static void emit_map(struct crush_map *map) {
    printf("     \"map\": {\n      \"tunables\": {"
           "\"choose_local_tries\": %u, \"choose_local_fallback_tries\": %u, "
           "\"choose_total_tries\": %u, \"chooseleaf_descend_once\": %u, "
           "\"chooseleaf_vary_r\": %u, \"chooseleaf_stable\": %u},\n",
           map->choose_local_tries, map->choose_local_fallback_tries,
           map->choose_total_tries, map->chooseleaf_descend_once,
           map->chooseleaf_vary_r, map->chooseleaf_stable);
    printf("      \"max_devices\": %d,\n      \"buckets\": [\n", map->max_devices);
    int firstb = 1;
    for (int b = 0; b < map->max_buckets; b++) {
        struct crush_bucket *bu = map->buckets[b];
        if (!bu) continue;
        printf("%s       {\"id\": %d, \"alg\": %d, \"type\": %d, "
               "\"weight\": %u, \"size\": %u, \"items\": [",
               firstb ? "" : ",\n", bu->id, bu->alg, bu->type, bu->weight,
               bu->size);
        firstb = 0;
        for (unsigned i = 0; i < bu->size; i++)
            printf("%s%d", i ? "," : "", bu->items[i]);
        printf("], ");
        switch (bu->alg) {
        case CRUSH_BUCKET_UNIFORM:
            printf("\"item_weight\": %u",
                   ((struct crush_bucket_uniform *)bu)->item_weight);
            break;
        case CRUSH_BUCKET_LIST: {
            struct crush_bucket_list *l = (struct crush_bucket_list *)bu;
            emit_u32s("item_weights", l->item_weights, bu->size);
            printf(", ");
            emit_u32s("sum_weights", l->sum_weights, bu->size);
            break;
        }
        case CRUSH_BUCKET_TREE: {
            struct crush_bucket_tree *t = (struct crush_bucket_tree *)bu;
            printf("\"num_nodes\": %u, ", t->num_nodes);
            emit_u32s("node_weights", t->node_weights, t->num_nodes);
            break;
        }
        case CRUSH_BUCKET_STRAW: {
            struct crush_bucket_straw *s = (struct crush_bucket_straw *)bu;
            emit_u32s("item_weights", s->item_weights, bu->size);
            printf(", ");
            emit_u32s("straws", s->straws, bu->size);
            break;
        }
        case CRUSH_BUCKET_STRAW2:
            emit_u32s("item_weights",
                      ((struct crush_bucket_straw2 *)bu)->item_weights,
                      bu->size);
            break;
        }
        printf("}");
    }
    printf("],\n      \"rules\": [\n");
    int firstr = 1;
    for (unsigned r = 0; r < map->max_rules; r++) {
        struct crush_rule *ru = map->rules[r];
        if (!ru) continue;
        printf("%s       {\"ruleno\": %u, \"steps\": [", firstr ? "" : ",\n", r);
        firstr = 0;
        for (unsigned s = 0; s < ru->len; s++)
            printf("%s[%u,%d,%d]", s ? "," : "", ru->steps[s].op,
                   ru->steps[s].arg1, ru->steps[s].arg2);
        printf("]}");
    }
    printf("]\n     },\n");
}

/* ---- runs -------------------------------------------------------------- */

static int add_bucket(struct crush_map *map, int alg, int type,
                      int size, int *items, int *weights) {
    struct crush_bucket *b = crush_make_bucket(map, alg, CRUSH_HASH_RJENKINS1,
                                               type, size, items, weights);
    int id;
    if (crush_add_bucket(map, 0, b, &id) < 0) exit(2);
    return id;
}

static int first_run;

static void run_rule(struct crush_map *map, int ruleno, int nx,
                     const __u32 *weight, int weight_max, int result_max,
                     const char *name) {
    void *cw = malloc(map->working_size + 3 * result_max * sizeof(int));
    int *result = malloc(sizeof(int) * result_max);
    printf("%s      {\"name\": \"%s\", \"ruleno\": %d, \"result_max\": %d, ",
           first_run ? "" : ",\n", name, ruleno, result_max);
    first_run = 0;
    emit_u32s("weights", weight, weight_max);
    printf(",\n       \"results\": [");
    for (int x = 0; x < nx; x++) {
        crush_init_workspace(map, cw);
        int len = crush_do_rule(map, ruleno, x, result, result_max,
                                weight, weight_max, cw, NULL);
        printf("%s[", x ? "," : "");
        for (int i = 0; i < len; i++)
            printf("%s%d", i ? "," : "", result[i]);
        printf("]");
    }
    printf("]}");
    free(result);
    free(cw);
}

static int first_group = 1;

static void begin_group(struct crush_map *map) {
    crush_finalize(map);
    printf("%s    {\n", first_group ? "" : ",\n");
    first_group = 0;
    emit_map(map);
    printf("     \"runs\": [\n");
    first_run = 1;
}

static void end_group(struct crush_map *map) {
    printf("]\n    }");
    crush_destroy(map);
}

#define NX 64

int main(int argc, char **argv) {
    if (argc > 1 && strcmp(argv[1], "lntable") == 0) {
        /* full straw2-domain crush_ln LUT: u in [0, 0xffff] */
        for (int i = 0; i <= 0xffff; i++)
            printf("%llu\n", golden_crush_ln((unsigned int)i));
        return 0;
    }
    printf("{\n");
    emit_hash_golden();
    emit_ln_golden();
    printf("  \"groups\": [\n");

    /* ---- flat root of 12 osds, straw2, uneven weights ---------------- */
    {
        struct crush_map *map = crush_create();
        int items[12], weights[12];
        for (int i = 0; i < 12; i++) {
            items[i] = i;
            weights[i] = 0x10000 * (1 + (i % 4));
        }
        int root = add_bucket(map, CRUSH_BUCKET_STRAW2, 1, 12, items, weights);
        struct crush_rule *r = crush_make_rule(3, 0, 1, 1, 12);
        crush_rule_set_step(r, 0, CRUSH_RULE_TAKE, root, 0);
        crush_rule_set_step(r, 1, CRUSH_RULE_CHOOSE_FIRSTN, 3, 0);
        crush_rule_set_step(r, 2, CRUSH_RULE_EMIT, 0, 0);
        int ruleno = crush_add_rule(map, r, -1);
        begin_group(map);
        __u32 w[12];
        for (int i = 0; i < 12; i++) w[i] = 0x10000;
        run_rule(map, ruleno, NX, w, 12, 3, "flat_straw2_firstn");
        w[3] = 0x8000; w[7] = 0; w[10] = 0x4000;
        run_rule(map, ruleno, NX, w, 12, 3, "flat_straw2_firstn_reweight");
        end_group(map);
    }

    /* ---- root -> 4 hosts x 4 osds: chooseleaf firstn/indep, choose ---- */
    {
        struct crush_map *map = crush_create();
        int hosts[4];
        for (int h = 0; h < 4; h++) {
            int items[4], weights[4];
            for (int i = 0; i < 4; i++) {
                items[i] = h * 4 + i;
                weights[i] = 0x10000 + 0x4000 * i;
            }
            hosts[h] = add_bucket(map, CRUSH_BUCKET_STRAW2, 1, 4, items, weights);
        }
        int hw[4];
        for (int h = 0; h < 4; h++) hw[h] = 0x10000 * (h + 2);
        int root = add_bucket(map, CRUSH_BUCKET_STRAW2, 2, 4, hosts, hw);

        struct crush_rule *rep = crush_make_rule(3, 0, 1, 1, 10);
        crush_rule_set_step(rep, 0, CRUSH_RULE_TAKE, root, 0);
        crush_rule_set_step(rep, 1, CRUSH_RULE_CHOOSELEAF_FIRSTN, 0, 1);
        crush_rule_set_step(rep, 2, CRUSH_RULE_EMIT, 0, 0);
        int r_rep = crush_add_rule(map, rep, -1);

        struct crush_rule *ec = crush_make_rule(3, 1, 3, 1, 10);
        crush_rule_set_step(ec, 0, CRUSH_RULE_TAKE, root, 0);
        crush_rule_set_step(ec, 1, CRUSH_RULE_CHOOSELEAF_INDEP, 0, 1);
        crush_rule_set_step(ec, 2, CRUSH_RULE_EMIT, 0, 0);
        int r_ec = crush_add_rule(map, ec, -1);

        struct crush_rule *two = crush_make_rule(4, 2, 1, 1, 10);
        crush_rule_set_step(two, 0, CRUSH_RULE_TAKE, root, 0);
        crush_rule_set_step(two, 1, CRUSH_RULE_CHOOSE_FIRSTN, 2, 1);
        crush_rule_set_step(two, 2, CRUSH_RULE_CHOOSE_FIRSTN, 2, 0);
        crush_rule_set_step(two, 3, CRUSH_RULE_EMIT, 0, 0);
        int r_two = crush_add_rule(map, two, -1);

        begin_group(map);
        __u32 w[16];
        for (int i = 0; i < 16; i++) w[i] = 0x10000;
        run_rule(map, r_rep, NX, w, 16, 3, "tree_chooseleaf_firstn");
        run_rule(map, r_ec, NX, w, 16, 6, "tree_chooseleaf_indep");
        run_rule(map, r_two, NX, w, 16, 4, "tree_choose_choose");
        w[4] = w[5] = w[6] = w[7] = 0;
        w[1] = 0x8000; w[13] = 0x2000;
        run_rule(map, r_rep, NX, w, 16, 3, "tree_chooseleaf_firstn_degraded");
        run_rule(map, r_ec, NX, w, 16, 6, "tree_chooseleaf_indep_degraded");
        end_group(map);
    }

    /* ---- legacy vs optimal tunables ---------------------------------- */
    for (int variant = 0; variant < 2; variant++) {
        struct crush_map *map = crush_create();
        if (variant == 0)
            set_legacy_crush_map(map);
        int hosts[3];
        for (int h = 0; h < 3; h++) {
            int items[3], weights[3];
            for (int i = 0; i < 3; i++) {
                items[i] = h * 3 + i;
                weights[i] = 0x10000 * (i + 1);
            }
            hosts[h] = add_bucket(map, CRUSH_BUCKET_STRAW2, 1, 3,
                                  items, weights);
        }
        int hw[3] = {0x30000, 0x60000, 0x90000};
        int root = add_bucket(map, CRUSH_BUCKET_STRAW2, 2, 3, hosts, hw);
        struct crush_rule *r = crush_make_rule(3, 0, 1, 1, 10);
        crush_rule_set_step(r, 0, CRUSH_RULE_TAKE, root, 0);
        crush_rule_set_step(r, 1, CRUSH_RULE_CHOOSELEAF_FIRSTN, 0, 1);
        crush_rule_set_step(r, 2, CRUSH_RULE_EMIT, 0, 0);
        int ruleno = crush_add_rule(map, r, -1);
        begin_group(map);
        __u32 w[9];
        for (int i = 0; i < 9; i++) w[i] = 0x10000;
        w[2] = 0x9999;
        run_rule(map, ruleno, NX, w, 9, 3,
                 variant == 0 ? "tunables_legacy" : "tunables_optimal");
        end_group(map);
    }

    /* ---- other bucket algorithms ------------------------------------- */
    {
        int algs[4] = {CRUSH_BUCKET_UNIFORM, CRUSH_BUCKET_LIST,
                       CRUSH_BUCKET_TREE, CRUSH_BUCKET_STRAW};
        const char *names[4] = {"alg_uniform", "alg_list", "alg_tree",
                                "alg_straw"};
        for (int a = 0; a < 4; a++) {
            struct crush_map *map = crush_create();
            int items[8], weights[8];
            for (int i = 0; i < 8; i++) {
                items[i] = i;
                weights[i] = (algs[a] == CRUSH_BUCKET_UNIFORM)
                                 ? 0x10000
                                 : 0x10000 + 0x2000 * i;
            }
            int root = add_bucket(map, algs[a], 1, 8, items, weights);
            struct crush_rule *r = crush_make_rule(3, 0, 1, 1, 8);
            crush_rule_set_step(r, 0, CRUSH_RULE_TAKE, root, 0);
            crush_rule_set_step(r, 1, CRUSH_RULE_CHOOSE_FIRSTN, 3, 0);
            crush_rule_set_step(r, 2, CRUSH_RULE_EMIT, 0, 0);
            int ruleno = crush_add_rule(map, r, -1);
            begin_group(map);
            __u32 w[8];
            for (int i = 0; i < 8; i++) w[i] = 0x10000;
            run_rule(map, ruleno, NX, w, 8, 3, names[a]);
            end_group(map);
        }
    }

    /* ---- device-class shadow tree (hand-built per CrushWrapper
     * device_class_clone semantics: per-class clone buckets holding only
     * the matching devices at their original weights, child clones at
     * their recomputed weights; CrushWrapper.cc:2648).  The python side
     * builds the FULL mixed map, calls device_class_clone, and must
     * place bit-identically to this reference-built shadow hierarchy. */
    {
        struct crush_map *map = crush_create();
        /* 4 hosts x 2 devices (even=ssd, odd=hdd), weights 1+d%3 */
        int full_hosts[4], ssd_hosts[4];
        for (int h = 0; h < 4; h++) {
            int items[2], weights[2];
            for (int i = 0; i < 2; i++) {
                items[i] = h * 2 + i;
                weights[i] = 0x10000 * (1 + (h * 2 + i) % 3);
            }
            full_hosts[h] = add_bucket(map, CRUSH_BUCKET_STRAW2, 1, 2,
                                       items, weights);
        }
        int fw[4];
        for (int h = 0; h < 4; h++) fw[h] = map->buckets[-1-full_hosts[h]]->weight;
        int full_root = add_bucket(map, CRUSH_BUCKET_STRAW2, 2, 4,
                                   full_hosts, fw);
        (void)full_root;
        /* the ssd shadow: one device (the even one) per host */
        for (int h = 0; h < 4; h++) {
            int items[1] = {h * 2};
            int weights[1] = {0x10000 * (1 + (h * 2) % 3)};
            ssd_hosts[h] = add_bucket(map, CRUSH_BUCKET_STRAW2, 1, 1,
                                      items, weights);
        }
        int sw[4];
        for (int h = 0; h < 4; h++) sw[h] = map->buckets[-1-ssd_hosts[h]]->weight;
        int ssd_root = add_bucket(map, CRUSH_BUCKET_STRAW2, 2, 4,
                                  ssd_hosts, sw);
        struct crush_rule *r = crush_make_rule(3, 0, 3, 1, 10);
        crush_rule_set_step(r, 0, CRUSH_RULE_TAKE, ssd_root, 0);
        crush_rule_set_step(r, 1, CRUSH_RULE_CHOOSELEAF_INDEP, 3, 1);
        crush_rule_set_step(r, 2, CRUSH_RULE_EMIT, 0, 0);
        int ruleno = crush_add_rule(map, r, -1);
        begin_group(map);
        __u32 w[8];
        for (int i = 0; i < 8; i++) w[i] = 0x10000;
        run_rule(map, ruleno, NX, w, 8, 3, "class_shadow_ssd");
        end_group(map);
    }

    /* ---- indep holes: numrep > healthy items ------------------------- */
    {
        struct crush_map *map = crush_create();
        int hosts[3];
        for (int h = 0; h < 3; h++) {
            int items[2], weights[2];
            for (int i = 0; i < 2; i++) {
                items[i] = h * 2 + i;
                weights[i] = 0x10000;
            }
            hosts[h] = add_bucket(map, CRUSH_BUCKET_STRAW2, 1, 2,
                                  items, weights);
        }
        int hw[3] = {0x20000, 0x20000, 0x20000};
        int root = add_bucket(map, CRUSH_BUCKET_STRAW2, 2, 3, hosts, hw);
        struct crush_rule *r = crush_make_rule(3, 0, 3, 1, 10);
        crush_rule_set_step(r, 0, CRUSH_RULE_TAKE, root, 0);
        crush_rule_set_step(r, 1, CRUSH_RULE_CHOOSELEAF_INDEP, 0, 1);
        crush_rule_set_step(r, 2, CRUSH_RULE_EMIT, 0, 0);
        int ruleno = crush_add_rule(map, r, -1);
        begin_group(map);
        __u32 w[6];
        for (int i = 0; i < 6; i++) w[i] = 0x10000;
        run_rule(map, ruleno, NX, w, 6, 5, "indep_holes");
        w[0] = w[1] = 0;
        run_rule(map, ruleno, NX, w, 6, 5, "indep_holes_host_down");
        end_group(map);
    }

    printf("\n  ]\n}\n");
    return 0;
}
