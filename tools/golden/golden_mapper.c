/* Separate TU for mapper.c (its statics collide with builder.c's). Exposes
 * the static crush_ln() via a wrapper for the golden generator. */
#include "mapper.c"

unsigned long long golden_crush_ln(unsigned int x) {
    return (unsigned long long)crush_ln(x);
}
