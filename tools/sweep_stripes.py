"""Quick (groups, tile_n) sweep for gf_apply_stripes_pallas on live TPU.

Uses bench.py's chain-difference timing so numbers are comparable to the
north-star metric.  Dev tool, not part of the suite.
"""
import functools
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench import per_op_seconds  # noqa: E402


def main():
    import jax
    import jax.numpy as jnp
    from ceph_tpu.ops import RSCodec
    from ceph_tpu.ops.pallas_kernels import gf_apply_stripes_pallas

    k, m, batch = 8, 4, 64
    n = 1024 * 1024 // k
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, size=(batch * k, n), dtype=np.uint8)
    codec = RSCodec(k, m, technique="cauchy", device="jax")
    dev = jax.device_put(jnp.asarray(data))
    pmat = jax.device_put(jnp.asarray(codec.parity_mat))
    D, _ = codec.decode_matrix([0, 9])
    dmat = jax.device_put(jnp.asarray(D))

    for groups in (2, 4, 8):
        for tile in (8192, 16384, 32768):
            fn = functools.partial(
                gf_apply_stripes_pallas, stripes=batch,
                groups=groups, tile_n=tile)

            def ap(M, Dd, _fn=fn):
                return _fn(M, Dd)

            try:
                enc = batch / per_op_seconds(ap, pmat, dev)
                dec = batch / per_op_seconds(ap, dmat, dev)
            except Exception as e:
                print(f"g={groups} t={tile}: FAIL {type(e).__name__}: {e}")
                continue
            print(f"g={groups} t={tile}: encode {enc:8.0f} "
                  f"decode {dec:8.0f} MiB/s", flush=True)


if __name__ == "__main__":
    main()
