#!/usr/bin/env python3
"""Soak sweep: many seeded campaigns; shrink any failure to a repro.

The hunt methodology that found this round's deepest bugs (scrub
blindness to post-overwrite bitrot, clones lost to log repair, recovery
laundering rot into parity, damage flags escaping through snapshot
COW/rollback): run `tests/test_soak.py`'s campaign across a seed range,
and on failure capture the action trace and greedily shrink it to a
minimal deterministic reproducer (the seed-113 chain reduced from 300
steps to 13 actions this way).

    JAX_PLATFORMS=cpu python tools/soak_sweep.py --seeds 200 300
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--seeds", nargs=2, type=int, default=[200, 240],
                    metavar=("LO", "HI"))
    ap.add_argument("--pool-types", nargs="+", default=["ec", "rep"])
    args = ap.parse_args(argv)

    import tests.test_soak as soak
    fails = []
    n = 0
    for seed in range(*args.seeds):
        for pt in args.pool_types:
            n += 1
            try:
                soak.test_soak_campaign(seed, pt)
            except Exception as e:
                fails.append((seed, pt, str(e)[:120]))
                print(f"FAIL seed={seed} {pt}: {e}", file=sys.stderr)
    print(f"{n} campaigns, {len(fails)} failures")
    if fails:
        print("shrink a failure with the exec-copy + greedy-removal "
              "recipe in the git history of tests/test_soak.py "
              "(commit 7a8df0e's message documents the workflow)")
    return 1 if fails else 0


if __name__ == "__main__":
    sys.exit(main())
