"""ceph-lint CLI: run the static-analysis rules over the tree.

Usage::

    python -m tools.ceph_lint                          # whole tree
    python -m tools.ceph_lint --baseline .ceph_lint_baseline.json
    python -m tools.ceph_lint --rules lock-order-cycle,jit-host-sync
    python -m tools.ceph_lint --list-rules
    python -m tools.ceph_lint --json                   # machine output

Exit status: 0 when every finding is baselined (or none exist),
1 when NEW findings are present.  The baseline workflow: a finding
that is reviewed and judged benign gets an entry in
``.ceph_lint_baseline.json`` with a ``justification`` — new code is
gated while legacy noise doesn't block.  Stale entries (the finding
no longer fires) are reported as warnings so the file stays honest.
"""
from __future__ import annotations

import argparse
import json
import sys
from collections import Counter


def _analysis():
    # deferred so --help stays fast and the module imports without
    # the repo root on sys.path costing anything
    import ceph_tpu.analysis as A
    return A


def lint_summary(baseline: str | None = None) -> dict:
    """The ``lint`` block bench.py embeds in its JSON artifact:
    per-rule finding counts plus the new-vs-baseline split, so
    perf_gate history shows the finding-count trajectory."""
    A = _analysis()
    findings = A.run_rules(A.default_index())
    base = A.load_baseline(baseline)
    new, suppressed, stale = A.split_by_baseline(findings, base)
    return {
        "total": len(findings),
        "new": len(new),
        "baselined": len(suppressed),
        "stale_baseline": len(stale),
        "rules_run": len(A.all_rules()),
        "by_rule": dict(sorted(Counter(
            f.rule for f in findings).items())),
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="ceph_lint",
        description="static analysis over ceph_tpu/, tools/, bench.py")
    ap.add_argument("--baseline", metavar="FILE", default=None,
                    help="suppression file; baselined findings don't "
                         "fail the run")
    ap.add_argument("--rules", metavar="ID[,ID...]", default=None,
                    help="run only these rule ids")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    ap.add_argument("--json", action="store_true",
                    help="emit findings + summary as JSON")
    args = ap.parse_args(argv)

    A = _analysis()
    if args.list_rules:
        for rid, r in sorted(A.all_rules().items()):
            print(f"{rid:24s} {r.severity:8s} {r.description}")
        return 0

    rule_ids = None
    if args.rules:
        rule_ids = tuple(s.strip() for s in args.rules.split(",")
                         if s.strip())
        unknown = [r for r in rule_ids if r not in A.all_rules()]
        if unknown:
            print(f"unknown rule ids: {', '.join(unknown)}",
                  file=sys.stderr)
            return 2

    findings = A.run_rules(A.default_index(), rule_ids)
    base = A.load_baseline(args.baseline) if args.baseline else {}
    new, suppressed, stale = A.split_by_baseline(findings, base)
    if rule_ids is not None:
        stale = [k for k in stale if k[0] in rule_ids]

    if args.json:
        print(json.dumps({
            "findings": [{"rule": f.rule, "path": f.path,
                          "line": f.line, "severity": f.severity,
                          "message": f.message,
                          "baselined": f.key in base}
                         for f in findings],
            "summary": {"total": len(findings), "new": len(new),
                        "baselined": len(suppressed),
                        "stale_baseline": len(stale)},
        }, indent=1))
        return 1 if new else 0

    for f in new:
        print(f.render())
    for k in stale:
        print(f"stale baseline entry (finding no longer fires): "
              f"[{k[0]}] {k[1]}: {k[2]}", file=sys.stderr)
    n_err = sum(1 for f in new if f.severity == "error")
    n_warn = len(new) - n_err
    print(f"ceph-lint: {len(new)} new "
          f"({n_err} errors, {n_warn} warnings), "
          f"{len(suppressed)} baselined, {len(stale)} stale baseline "
          f"entries, {len(A.all_rules() if rule_ids is None else rule_ids)} "
          f"rules run")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
