#!/usr/bin/env python3
"""ts_report: sparkline/percentile tables from an embedded time-series.

Post-hoc analysis of a soak or bench run without an external scraper:
mgr/timeseries.py records the stats digest + heat + wire rollups into a
bounded ring, the flight recorder dumps that ring into every bundle, and
THIS tool renders it back — per-series count/min/p50/p95/max plus an
ascii sparkline — so "what did the tail look like around the incident"
is answered from the artifact alone (the flight-recorder promise applied
to time series).

Inputs, auto-detected:

- a flight bundle (``flight-*.json``) — uses its ``timeseries`` source,
  and ``--log`` replays its ``clusterlog`` entries alongside;
- a bare ``TimeSeriesRing.dump()`` JSON;
- a directory — the newest ``flight-*.json`` beneath it (e.g.
  ``<data_dir>/flight``).

Stdlib-only, standalone (tools/trace_report.py's discipline).

    python tools/ts_report.py DATA_DIR/flight
    python tools/ts_report.py flight-...-health-OSD_DOWN.json --log
    python tools/ts_report.py bundle.json --series tail_ --coarse
"""
from __future__ import annotations

import argparse
import glob
import importlib.util
import json
import os
import sys

SPARK = "▁▂▃▄▅▆▇█"

# THE shared nearest-rank percentile (ceph_tpu/common/percentile.py),
# loaded by PATH so this tool stays standalone.  The local copy this
# replaced had silently drifted to a floor-index definition — the exact
# failure mode the shared helper + AST guard (tests/test_critpath.py)
# exist to prevent.
_PCTL_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          os.pardir, "ceph_tpu", "common",
                          "percentile.py")
_spec = importlib.util.spec_from_file_location("_ceph_tpu_percentile",
                                               _PCTL_PATH)
_pctl = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_pctl)


def sparkline(values: list[float], width: int = 32) -> str:
    """Downsample to ``width`` buckets (max per bucket — spikes must
    survive) and render with eighth-block glyphs."""
    if not values:
        return ""
    if len(values) > width:
        per = len(values) / width
        values = [max(values[int(i * per):max(int(i * per) + 1,
                                              int((i + 1) * per))])
                  for i in range(width)]
    lo, hi = min(values), max(values)
    span = hi - lo
    if span <= 0:
        return SPARK[0] * len(values)
    return "".join(SPARK[min(len(SPARK) - 1,
                             int((v - lo) / span * len(SPARK)))]
                   for v in values)


def _p(sorted_vals: list[float], q: float) -> float:
    """Shared nearest-rank percentile over a pre-sorted list."""
    return _pctl.nearest_rank(sorted_vals, q)


def load_timeseries(path: str) -> tuple[dict, dict | None]:
    """(timeseries dump, enclosing flight bundle or None)."""
    if os.path.isdir(path):
        bundles = sorted(glob.glob(os.path.join(path, "flight-*.json")),
                         key=os.path.getmtime)
        if not bundles:
            raise FileNotFoundError(f"no flight-*.json under {path}")
        path = bundles[-1]
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: not a JSON object")
    if "fine" in doc and "coarse" in doc:
        return doc, None                       # bare ring dump
    ts = doc.get("timeseries")
    if not isinstance(ts, dict) or "fine" not in ts:
        raise ValueError(f"{path}: no usable timeseries source "
                         f"(keys: {sorted(doc)[:12]})")
    return ts, doc


def series_table(ts: dict, match: str | None = None,
                 coarse: bool = False) -> list[dict]:
    points = ts.get("coarse" if coarse else "fine", [])
    names = sorted({k for p in points for k in p
                    if k not in ("t", "wall", "n")})
    rows = []
    for name in names:
        if match and match not in name:
            continue
        vals = [float(p[name]) for p in points if name in p]
        if not vals:
            continue
        s = sorted(vals)
        rows.append({"series": name, "n": len(vals),
                     "min": s[0], "p50": _p(s, 50),
                     "p95": _p(s, 95), "max": s[-1],
                     "spark": sparkline(vals)})
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="render an embedded time-series ring (flight bundle "
                    "or bare dump) as sparkline/percentile tables")
    ap.add_argument("path", help="flight bundle, ring dump, or a "
                                 "directory of flight-*.json")
    ap.add_argument("--series", help="only series containing this "
                                     "substring")
    ap.add_argument("--coarse", action="store_true",
                    help="use the coarse (mean+max folded) archive")
    ap.add_argument("--log", action="store_true",
                    help="also replay the bundle's clusterlog entries")
    ap.add_argument("--json", action="store_true",
                    help="emit the table as JSON")
    args = ap.parse_args(argv)

    try:
        ts, bundle = load_timeseries(args.path)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    rows = series_table(ts, args.series, args.coarse)
    if args.json:
        print(json.dumps({"points": len(ts.get("fine", [])),
                          "interval_s": ts.get("interval_s"),
                          "series": rows}, default=str))
    else:
        print(f"# {len(ts.get('fine', []))} fine / "
              f"{len(ts.get('coarse', []))} coarse points, "
              f"interval {ts.get('interval_s')}s")
        if not rows:
            print("(no matching series)")
        w = max((len(r["series"]) for r in rows), default=6)
        for r in rows:
            print(f"{r['series']:<{w}}  n={r['n']:<4} "
                  f"min={r['min']:<10.3f} p50={r['p50']:<10.3f} "
                  f"p95={r['p95']:<10.3f} max={r['max']:<10.3f} "
                  f"{r['spark']}")
    if args.log and bundle is not None:
        entries = bundle.get("clusterlog")
        if isinstance(entries, list):
            print(f"# clusterlog ({len(entries)} entries)")
            sys.path.insert(0, os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))
            try:
                from ceph_tpu.common.clusterlog import format_entry
            except ImportError:      # stay standalone even off-tree
                def format_entry(e):
                    return (f"{e.get('time')} {e.get('severity')} "
                            f"[{e.get('channel')}] {e.get('message')}")
            for e in entries:
                print(format_entry(e))
    return 0


if __name__ == "__main__":
    sys.exit(main())
