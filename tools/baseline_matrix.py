"""Fill BASELINE.md's run matrix: the five measured configs.

The reference publishes no absolute EC numbers (BASELINE.md), so every
number here is measured on the host/device this script runs on, with the
methodology of the reference harnesses it mirrors:

  1. CPU baseline          ceph_erasure_code_benchmark --plugin jerasure/isa
                           (src/test/erasure-code/ceph_erasure_code_benchmark.cc:151-181)
                           -> native cpp_rs plugin (gf8_simd: GFNI/AVX-512
                           or AVX2 pshufb), RS(4,2) and RS(8,4), 1 MiB.
  2. single-stripe jax_rs  same harness, --plugin jax_rs, one 1 MiB stripe
                           per call INCLUDING host->device transfer, plus
                           the plugin's auto-routed path (which sends
                           sub-threshold calls to the SIMD CPU codec —
                           the framework's answer to dispatch economics).
  3. batched device path   C++ BatchQueue -> coalesce -> one JAX dispatch
                           (the sidecar product path): throughput vs batch
                           size curve.
  4. cluster-level         rados bench on a MiniCluster EC pool
                           (qa/standalone/erasure-code/test-erasure-code.sh:21-66).
  5. bulk placement        osdmaptool --test-map-pgs analog: all PGs of a
                           pool through the vmapped JAX mapper vs the
                           scalar host interpreter, with bit-equality.

Writes BASELINE_RESULTS.json and prints a markdown table for BASELINE.md.

Usage: python tools/baseline_matrix.py [--quick] [--only N[,N...]]
"""
from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

sys.path.insert(0, ".")

MIB = 2**20


def timeit(fn, iters, warmup=2):
    for _ in range(warmup):
        fn()
    t0 = time.perf_counter()
    for _ in range(iters):
        fn()
    return (time.perf_counter() - t0) / iters


def config1_cpu_baseline(quick: bool) -> dict:
    """Native SIMD CPU codec through the plugin path, 1 MiB buffers."""
    from ceph_tpu.native import NativeRegistry, registry_lib
    reg = NativeRegistry()
    level = registry_lib().ec_simd_level()
    out = {"simd_level": level,
           "simd_name": {0: "scalar", 1: "avx2", 2: "gfni+avx2",
                         3: "gfni+avx512"}[level]}
    iters = 10 if quick else 50
    for k, m in ((4, 2), (8, 4)):
        ec = reg.factory("cpp_rs", {"k": str(k), "m": str(m),
                                    "technique": "reed_sol_van"})
        chunk = MIB // k
        rng = np.random.default_rng(0)
        data = np.ascontiguousarray(
            rng.integers(0, 256, size=(k, chunk), dtype=np.uint8))
        t_enc = timeit(lambda: ec.encode(data), iters)
        parity = ec.encode(data)
        erased = [0, k]                      # 1 data + 1 parity
        avail = {i: data[i] for i in range(1, k)}
        avail |= {k + j: parity[j] for j in range(1, m)}
        t_dec = timeit(lambda: ec.decode(avail, erased, chunk), iters)
        out[f"rs_k{k}m{m}"] = {
            "encode_mibs": round(1.0 / t_enc, 1),
            "decode_mibs": round(1.0 / t_dec, 1),
        }
    return out


def config2_single_stripe(quick: bool) -> dict:
    """One 1 MiB stripe per call: device path incl. transfer, and the
    plugin's auto route."""
    import jax
    from ceph_tpu.ops import RSCodec
    k, m = 8, 4
    chunk = MIB // k
    rng = np.random.default_rng(1)
    data = np.ascontiguousarray(
        rng.integers(0, 256, size=(k, chunk), dtype=np.uint8))
    iters = 3 if quick else 10

    dev = RSCodec(k, m, technique="reed_sol_van", device="jax")
    t_dev = timeit(lambda: np.asarray(dev.encode(data)), iters, warmup=1)

    from ceph_tpu.plugins.registry import ErasureCodePluginRegistry
    auto = ErasureCodePluginRegistry.instance().factory(
        "jax_rs", "", {"k": str(k), "m": str(m), "device": "auto"})
    bufs = {i: (data[i].copy() if i < k else np.zeros(chunk, np.uint8))
            for i in range(k + m)}
    t_auto = timeit(
        lambda: auto.encode_chunks(set(range(k + m)), bufs), iters)

    cpu = RSCodec(k, m, technique="reed_sol_van", device="numpy")
    t_cpu = timeit(lambda: cpu.encode(data), iters)
    return {
        "platform": jax.devices()[0].platform,
        "device_incl_transfer_mibs": round(1.0 / t_dev, 1),
        "auto_routed_mibs": round(1.0 / t_auto, 1),
        "cpu_forced_mibs": round(1.0 / t_cpu, 1),
        "note": "device path moves k+m chunks across the host<->device "
                "link per call (tunnel-bound under axon); the auto route "
                "compares against ec_device_threshold_bytes; cpu_forced "
                "is the SIMD host codec on the same call shape",
    }


def config3_batch_queue(quick: bool) -> dict:
    """C++ batch queue -> JAX dispatch: throughput vs batch size."""
    import jax
    import jax.numpy as jnp
    from ceph_tpu.native import BatchQueue
    from ceph_tpu.ops import RSCodec
    k, m, chunk = 8, 4, 4096
    codec = RSCodec(k, m, technique="reed_sol_van", device="jax")
    pmat = jax.device_put(jnp.asarray(codec.parity_mat))

    from ceph_tpu.ops import rs_kernels

    @jax.jit
    def kernel(batch):                       # [n, k, chunk] -> [n, m, chunk]
        flat = batch.transpose(1, 0, 2).reshape(k, -1)
        par = rs_kernels.gf_apply(pmat, flat, "auto")
        return par.reshape(m, -1, chunk).transpose(1, 0, 2)

    rng = np.random.default_rng(2)
    stripes_total = 256 if quick else 1024
    curve = []
    for max_batch in (1, 4, 16, 64, 256):
        def fn(data, n, c, _mb=max_batch):
            # pad partial batches to the coalescing cap: one static shape
            # per queue, so nothing recompiles inside the timed region
            if n < _mb:
                data = np.concatenate(
                    [data, np.zeros((_mb - n, k, c), np.uint8)])
            return np.asarray(kernel(jnp.asarray(data)))[:n]

        q = BatchQueue(k, m, chunk, fn, max_batch=max_batch)
        data = [np.ascontiguousarray(
            rng.integers(0, 256, size=(k, chunk), dtype=np.uint8))
            for _ in range(stripes_total)]
        q.submit(data[0]); q.flush()         # warm compile
        t0 = time.perf_counter()
        for d in data:
            q.submit(d)
        q.flush()
        dt = time.perf_counter() - t0
        batches = q.batches
        q.close()
        curve.append({
            "max_batch": max_batch,
            "stripes_per_s": round(stripes_total / dt, 1),
            "mibs": round(stripes_total * k * chunk / MIB / dt, 1),
            "dispatches": batches,
        })
    return {"k": k, "m": m, "chunk": chunk, "curve": curve}


def config4_rados_bench(quick: bool) -> dict:
    """Cluster-level write/read bench on a MiniCluster EC pool."""
    import io
    from ceph_tpu.cluster import MiniCluster
    from ceph_tpu.bench.rados_bench import write_bench, seq_read_bench
    secs = 3 if quick else 10
    mc = MiniCluster(n_osds=12, osds_per_host=3)
    pid = mc.create_ec_pool("bench", {"plugin": "jax_rs", "k": "4",
                                      "m": "2"}, pg_num=8)
    sink = io.StringIO()
    w = write_bench(mc, pid, secs, 4 * MIB, concurrency=16, out=sink)
    r = seq_read_bench(mc, pid, w["ops"], 4 * MIB, out=sink)
    return {
        "write_mb_s": round(w["bandwidth_mb_s"], 1),
        "write_iops": round(w["iops"], 1),
        "read_mb_s": round(r["bandwidth_mb_s"], 1),
        "read_iops": round(r["iops"], 1),
        "seconds": secs,
    }


def config5_bulk_placement(quick: bool) -> dict:
    """All PGs of a pool: vmapped JAX mapper vs scalar host interpreter."""
    import jax
    jax.config.update("jax_enable_x64", True)   # exact straw2 draws
    from ceph_tpu.crush.map import (CRUSH_BUCKET_STRAW2,
                                    CRUSH_RULE_CHOOSELEAF_INDEP,
                                    CRUSH_RULE_EMIT, CRUSH_RULE_TAKE,
                                    CrushMap)
    from ceph_tpu.osdmap.osdmap import OSDMap
    from ceph_tpu.osdmap.types import PG, Pool, POOL_TYPE_ERASURE
    from ceph_tpu.osdmap.bulk import BulkPGMapper

    n_osds = 256
    pg_num = 4096 if quick else 32768
    cmap = CrushMap()
    cmap.set_type_name(1, "host")
    cmap.set_type_name(2, "root")
    hosts = []
    for h0 in range(0, n_osds, 8):
        items = list(range(h0, h0 + 8))
        hosts.append(cmap.add_bucket(
            CRUSH_BUCKET_STRAW2, 1, items, [0x10000] * len(items)))
    root = cmap.add_bucket(CRUSH_BUCKET_STRAW2, 2, hosts,
                           [sum(cmap.buckets[h].item_weights)
                            for h in hosts])
    cmap.finalize()
    ruleno = cmap.add_rule([(CRUSH_RULE_TAKE, root, 0),
                            (CRUSH_RULE_CHOOSELEAF_INDEP, 6, 1),
                            (CRUSH_RULE_EMIT, 0, 0)])
    m = OSDMap(crush=cmap)
    for o in range(n_osds):
        m.create_osd(o)
    pool = Pool(pool_id=1, type=POOL_TYPE_ERASURE, size=6, min_size=5,
                pg_num=pg_num, crush_rule=ruleno, name="bulk")
    m.add_pool(pool)

    t0 = time.perf_counter()
    host = [m.pg_to_up_acting_osds(PG(1, ps))[2] for ps in range(pg_num)]
    t_host = time.perf_counter() - t0

    mapper = BulkPGMapper(m)
    mapping = mapper.map_pool(1)             # includes jit compile
    t0 = time.perf_counter()
    mapping = mapper.map_pool(1)
    t_jax = time.perf_counter() - t0

    mismatch = sum(
        1 for ps in range(pg_num)
        if list(mapping.acting[ps][:len(host[ps])]) != list(host[ps]))
    return {
        "pg_num": pg_num, "n_osds": n_osds,
        "host_pgs_per_s": round(pg_num / t_host, 1),
        "jax_pgs_per_s": round(pg_num / t_jax, 1),
        "speedup": round(t_host / t_jax, 1),
        "mismatches": mismatch,
    }


CONFIGS = {
    1: ("cpu_baseline_simd", config1_cpu_baseline),
    2: ("single_stripe_incl_transfer", config2_single_stripe),
    3: ("batch_queue_curve", config3_batch_queue),
    4: ("rados_bench_minicluster", config4_rados_bench),
    5: ("bulk_placement", config5_bulk_placement),
}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--only", default="")
    ap.add_argument("--out", default="BASELINE_RESULTS.json")
    args = ap.parse_args()
    only = {int(x) for x in args.only.split(",") if x} or set(CONFIGS)

    results = {}
    try:
        with open(args.out) as f:
            results = json.load(f)
    except (OSError, ValueError):
        pass
    for n, (name, fn) in sorted(CONFIGS.items()):
        if n not in only:
            continue
        print(f"# config {n}: {name} ...", file=sys.stderr, flush=True)
        try:
            results[name] = fn(args.quick)
        except Exception as e:               # record the failure honestly
            results[name] = {"error": f"{type(e).__name__}: {e}"}
        print(json.dumps({name: results[name]}), flush=True)
    results["_meta"] = {"ts": time.time(), "quick": args.quick}
    with open(args.out, "w") as f:
        json.dump(results, f, indent=1, sort_keys=True)
    print(f"# wrote {args.out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
