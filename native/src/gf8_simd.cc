/* SIMD region kernels for GF(2^8) multiply-accumulate.
 *
 * The honest CPU baseline the TPU path is measured against: the same
 * techniques the reference's isa-l submodule uses on x86 —
 * GF2P8AFFINEQB (GFNI) where available, else the classic split-nibble
 * PSHUFB trick (isa-l's gf_vect_mul/gf_Nvect_mad family; cf. the
 * reference wiring at src/erasure-code/isa/ErasureCodeIsa.cc:119-131
 * ec_encode_data).  Structure follows isa-l's mad kernels: iterate over
 * 32-byte position blocks, keep all nout accumulators in registers, and
 * stream each input region exactly once, so the pass is memory-minimal
 * (k reads + m writes total, not k*m passes).
 *
 * Field semantics are gf8's poly 0x11D; GFNI's GF2P8MULB is hardwired to
 * 0x11B so only the *affine* instruction is usable: multiplication by a
 * constant c is linear over GF(2), i.e. one 8x8 bit-matrix per
 * coefficient, applied by GF2P8AFFINEQB in any field representation.
 */
#include "gf8.h"

#include <immintrin.h>

#include <cstring>
#include <ctime>
#include <mutex>
#include <vector>

namespace gf8 {

int simd_level() {
    static int level = [] {
        __builtin_cpu_init();
        if (__builtin_cpu_supports("gfni") &&
            __builtin_cpu_supports("avx512f") &&
            __builtin_cpu_supports("avx512bw"))
            return 3;
        if (__builtin_cpu_supports("gfni") && __builtin_cpu_supports("avx2"))
            return 2;
        if (__builtin_cpu_supports("avx2")) return 1;
        return 0;
    }();
    return level;
}

namespace {

/* scalar cleanup for the <32-byte tail of each region */
void scalar_tail(const uint8_t *coef, int nout, int nin,
                 const uint8_t *const *in, uint8_t *const *out,
                 size_t from, size_t to) {
    for (int r = 0; r < nout; r++) {
        uint8_t *dst = out[r];
        for (size_t i = from; i < to; i++) dst[i] = 0;
        for (int j = 0; j < nin; j++) {
            uint8_t c = coef[(size_t)r * nin + j];
            if (!c) continue;
            const uint8_t *row = MUL[c];
            const uint8_t *srcp = in[j];
            for (size_t i = from; i < to; i++) dst[i] ^= row[srcp[i]];
        }
    }
}

/* 8x8 GF(2) bit-matrix for multiplication by c, in GF2P8AFFINEQB's layout:
 * qword byte (7-q) holds the row producing output bit q; row bit p
 * multiplies input bit p (Intel SDM affine_byte operation). */
uint64_t affine_qword(uint8_t c) {
    uint64_t a = 0;
    for (int q = 0; q < 8; q++) {
        uint8_t row = 0;
        for (int p = 0; p < 8; p++)
            if ((MUL[c][1u << p] >> q) & 1) row |= (uint8_t)(1u << p);
        a |= (uint64_t)row << (8 * (7 - q));
    }
    return a;
}

constexpr int MAX_ACC = 8;   /* register accumulators per position block */

__attribute__((target("gfni,avx2")))
void block_pass_gfni(const uint8_t *coef, int nout, int nin,
                     const uint8_t *const *in, uint8_t *const *out,
                     size_t blocks) {
    /* precompute the affine matrix per (r, j) coefficient */
    __m256i mats[MAX_ACC * 32];
    for (int r = 0; r < nout; r++)
        for (int j = 0; j < nin; j++)
            mats[r * nin + j] = _mm256_set1_epi64x(
                (long long)affine_qword(coef[(size_t)r * nin + j]));
    for (size_t b = 0; b < blocks; b++) {
        const size_t off = b * 32;
        __m256i acc[MAX_ACC];
        for (int r = 0; r < nout; r++) acc[r] = _mm256_setzero_si256();
        for (int j = 0; j < nin; j++) {
            __m256i x = _mm256_loadu_si256(
                (const __m256i *)(in[j] + off));
            for (int r = 0; r < nout; r++) {
                uint8_t c = coef[(size_t)r * nin + j];
                if (!c) continue;
                acc[r] = _mm256_xor_si256(
                    acc[r],
                    _mm256_gf2p8affine_epi64_epi8(x, mats[r * nin + j], 0));
            }
        }
        for (int r = 0; r < nout; r++)
            _mm256_storeu_si256((__m256i *)(out[r] + off), acc[r]);
    }
}

__attribute__((target("gfni,avx512f,avx512bw")))
void block_pass_gfni512(const uint8_t *coef, int nout, int nin,
                        const uint8_t *const *in, uint8_t *const *out,
                        size_t blocks64) {
    __m512i mats[MAX_ACC * 32];
    for (int r = 0; r < nout; r++)
        for (int j = 0; j < nin; j++)
            mats[r * nin + j] = _mm512_set1_epi64(
                (long long)affine_qword(coef[(size_t)r * nin + j]));
    for (size_t b = 0; b < blocks64; b++) {
        const size_t off = b * 64;
        __m512i acc[MAX_ACC];
        for (int r = 0; r < nout; r++) acc[r] = _mm512_setzero_si512();
        for (int j = 0; j < nin; j++) {
            __m512i x = _mm512_loadu_si512(
                (const void *)(in[j] + off));
            for (int r = 0; r < nout; r++) {
                uint8_t c = coef[(size_t)r * nin + j];
                if (!c) continue;
                acc[r] = _mm512_xor_si512(
                    acc[r],
                    _mm512_gf2p8affine_epi64_epi8(x, mats[r * nin + j], 0));
            }
        }
        for (int r = 0; r < nout; r++)
            _mm512_storeu_si512((void *)(out[r] + off), acc[r]);
    }
}

__attribute__((target("avx2")))
void block_pass_avx2(const uint8_t *coef, int nout, int nin,
                     const uint8_t *const *in, uint8_t *const *out,
                     size_t blocks) {
    /* split-nibble tables per (r, j): lo[i] = c*i, hi[i] = c*(i<<4),
     * broadcast to both 128-bit lanes for VPSHUFB */
    __m256i tlo[MAX_ACC * 32], thi[MAX_ACC * 32];
    for (int r = 0; r < nout; r++)
        for (int j = 0; j < nin; j++) {
            uint8_t c = coef[(size_t)r * nin + j];
            alignas(32) uint8_t lo[32], hi[32];
            for (int i = 0; i < 16; i++) {
                lo[i] = lo[i + 16] = MUL[c][i];
                hi[i] = hi[i + 16] = MUL[c][i << 4];
            }
            tlo[r * nin + j] = _mm256_load_si256((const __m256i *)lo);
            thi[r * nin + j] = _mm256_load_si256((const __m256i *)hi);
        }
    const __m256i nib = _mm256_set1_epi8(0x0f);
    for (size_t b = 0; b < blocks; b++) {
        const size_t off = b * 32;
        __m256i acc[MAX_ACC];
        for (int r = 0; r < nout; r++) acc[r] = _mm256_setzero_si256();
        for (int j = 0; j < nin; j++) {
            __m256i x = _mm256_loadu_si256(
                (const __m256i *)(in[j] + off));
            __m256i xl = _mm256_and_si256(x, nib);
            __m256i xh = _mm256_and_si256(_mm256_srli_epi64(x, 4), nib);
            for (int r = 0; r < nout; r++) {
                uint8_t c = coef[(size_t)r * nin + j];
                if (!c) continue;
                __m256i p = _mm256_xor_si256(
                    _mm256_shuffle_epi8(tlo[r * nin + j], xl),
                    _mm256_shuffle_epi8(thi[r * nin + j], xh));
                acc[r] = _mm256_xor_si256(acc[r], p);
            }
        }
        for (int r = 0; r < nout; r++)
            _mm256_storeu_si256((__m256i *)(out[r] + off), acc[r]);
    }
}

bool gfni_verified() {
    /* one-time self-check of the affine bit convention against the
     * scalar tables; falls back to pshufb if the layout ever mismatches */
    static bool ok = [] {
        gf8::init_tables();  /* the check compares against MUL; an empty
                              * table would vacuously pass and pin GFNI on */
        if (simd_level() < 2) return false;
        alignas(32) uint8_t src[32], dst[32];
        for (int i = 0; i < 32; i++) src[i] = (uint8_t)(i * 7 + 3);
        const uint8_t coef = 0x8e;   /* a full-width constant */
        const uint8_t *inp[1] = {src};
        uint8_t *outp[1] = {dst};
        block_pass_gfni(&coef, 1, 1, inp, outp, 1);
        for (int i = 0; i < 32; i++)
            if (dst[i] != MUL[coef][src[i]]) return false;
        return true;
    }();
    return ok;
}

}  // namespace

bool simd_apply_matrix_ptrs(const uint8_t *coef, int nout, int nin,
                            const uint8_t *const *in, uint8_t *const *out,
                            size_t chunk_size) {
    if (nout <= 0 || nin <= 0 || nin > 32 || chunk_size < 32)
        return false;
    int level = simd_level();
    if (level == 0) return false;
    const bool gfni = level >= 2 && gfni_verified();
    const bool wide = gfni && level >= 3 && chunk_size >= 64;
    /* zmm path handles 64-byte blocks; remainder falls to the 32-byte
     * ymm pass, then a scalar tail */
    size_t blocks64 = wide ? chunk_size / 64 : 0;
    size_t done = blocks64 * 64;
    size_t blocks32 = (chunk_size - done) / 32;
    /* wide outputs run in register-sized row groups */
    for (int r0 = 0; r0 < nout; r0 += MAX_ACC) {
        int rows = nout - r0 < MAX_ACC ? nout - r0 : MAX_ACC;
        const uint8_t *c0 = coef + (size_t)r0 * nin;
        uint8_t *const *o0 = out + r0;
        if (wide)
            block_pass_gfni512(c0, rows, nin, in, o0, blocks64);
        if (blocks32) {
            const uint8_t *inp32[32];
            uint8_t *outp32[MAX_ACC];
            for (int j = 0; j < nin; j++) inp32[j] = in[j] + done;
            for (int r = 0; r < rows; r++) outp32[r] = o0[r] + done;
            if (gfni)
                block_pass_gfni(c0, rows, nin, inp32, outp32, blocks32);
            else
                block_pass_avx2(c0, rows, nin, inp32, outp32, blocks32);
        }
        size_t vec_done = done + blocks32 * 32;
        if (vec_done < chunk_size)
            scalar_tail(c0, rows, nin, in, o0, vec_done, chunk_size);
    }
    return true;
}

}  // namespace gf8

/* C entry points for introspection and in-process benchmarking (no
 * Python/ctypes overhead in the timed loop). */
extern "C" int ec_simd_level(void) { return gf8::simd_level(); }

extern "C" double ec_bench_apply(int nout, int nin, size_t chunk_size,
                                 int iters) {
    gf8::init_tables();
    std::vector<uint8_t> coef((size_t)nout * nin);
    for (size_t i = 0; i < coef.size(); i++) coef[i] = (uint8_t)(i * 37 + 5);
    std::vector<std::vector<uint8_t>> in(nin), out(nout);
    std::vector<const uint8_t *> inp;
    std::vector<uint8_t *> outp;
    for (int j = 0; j < nin; j++) {
        in[j].resize(chunk_size);
        for (size_t i = 0; i < chunk_size; i++)
            in[j][i] = (uint8_t)(i + j);
        inp.push_back(in[j].data());
    }
    for (int r = 0; r < nout; r++) {
        out[r].resize(chunk_size);
        outp.push_back(out[r].data());
    }
    gf8::apply_matrix_ptrs(coef.data(), nout, nin, inp.data(), outp.data(),
                           chunk_size);   /* warm */
    struct timespec t0, t1;
    clock_gettime(CLOCK_MONOTONIC, &t0);
    for (int i = 0; i < iters; i++)
        gf8::apply_matrix_ptrs(coef.data(), nout, nin, inp.data(),
                               outp.data(), chunk_size);
    clock_gettime(CLOCK_MONOTONIC, &t1);
    return (t1.tv_sec - t0.tv_sec) + (t1.tv_nsec - t0.tv_nsec) * 1e-9;
}

extern "C" int ec_apply_matrix(const unsigned char *coef, int nout, int nin,
                               const unsigned char *in, unsigned char *out,
                               size_t chunk_size) {
    gf8::init_tables();
    gf8::apply_matrix(coef, nout, nin, in, out, chunk_size);
    return 0;
}

/* crc32c (Castagnoli), raw reflected update without final xor — the
 * ceph_crc32c contract HashInfo chains per shard.  SSE4.2's CRC32
 * instruction computes exactly this polynomial; scalar slice-by-8
 * fallback elsewhere. */
namespace {

uint32_t crc32c_sw(uint32_t crc, const unsigned char *p, size_t n) {
    static uint32_t T[8][256];
    static std::once_flag once;
    std::call_once(once, [] {
        for (uint32_t i = 0; i < 256; i++) {
            uint32_t c = i;
            for (int j = 0; j < 8; j++)
                c = (c & 1) ? (c >> 1) ^ 0x82F63B78u : c >> 1;
            T[0][i] = c;
        }
        for (int t = 1; t < 8; t++)
            for (uint32_t i = 0; i < 256; i++)
                T[t][i] = (T[t - 1][i] >> 8) ^ T[0][T[t - 1][i] & 0xFF];
    });
    while (n >= 8) {
        crc ^= (uint32_t)p[0] | ((uint32_t)p[1] << 8) |
               ((uint32_t)p[2] << 16) | ((uint32_t)p[3] << 24);
        crc = T[7][crc & 0xFF] ^ T[6][(crc >> 8) & 0xFF] ^
              T[5][(crc >> 16) & 0xFF] ^ T[4][crc >> 24] ^
              T[3][p[4]] ^ T[2][p[5]] ^ T[1][p[6]] ^ T[0][p[7]];
        p += 8;
        n -= 8;
    }
    while (n--) {
        crc = T[0][(crc ^ *p++) & 0xFF] ^ (crc >> 8);
    }
    return crc;
}

__attribute__((target("sse4.2")))
uint32_t crc32c_hw(uint32_t crc, const unsigned char *p, size_t n) {
    uint64_t c = crc;
    while (n >= 8) {
        uint64_t v;
        std::memcpy(&v, p, 8);
        c = _mm_crc32_u64(c, v);
        p += 8;
        n -= 8;
    }
    uint32_t c32 = (uint32_t)c;
    while (n--) c32 = _mm_crc32_u8(c32, *p++);
    return c32;
}

}  // namespace

extern "C" uint32_t ec_crc32c(uint32_t seed, const unsigned char *p,
                              size_t n) {
    __builtin_cpu_init();
    static const bool hw = __builtin_cpu_supports("sse4.2");
    return hw ? crc32c_hw(seed, p, n) : crc32c_sw(seed, p, n);
}
