/* Stripe-batching dispatch queue: the host side of the TPU sidecar boundary.
 *
 * The reference encodes one stripe per call from the OSD write pipeline
 * (reference: src/osd/ECUtil.cc:136-148 — the per-stripe loop SURVEY.md §2.2
 * flags as the TPU batching hook).  This queue restructures that: producer
 * threads (the PG workers) submit stripes; a collector thread coalesces
 * them into one contiguous [n_stripes, k, chunk] batch and hands it to a
 * registered callback — the JAX sidecar's batched device dispatch — then
 * completes each stripe's ticket.  Dispatch fires when `max_batch` stripes
 * are pending or when the queue drains (adaptive batching, the same
 * accumulate-then-launch economics as SURVEY.md §7 step 3).
 *
 * C ABI so Python can drive it via ctypes and register a CFUNCTYPE callback.
 */
#include <condition_variable>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

extern "C" {

/* batch callback: data = n_stripes contiguous stripes of k*chunk bytes,
 * parity_out = n_stripes contiguous stripes of m*chunk bytes.
 * Returns 0 on success (nonzero fails every stripe in the batch). */
typedef int (*ec_batch_fn)(void *ctx, const unsigned char *data,
                           unsigned char *parity_out, size_t n_stripes,
                           size_t chunk_size);
typedef void (*ec_done_fn)(void *done_ctx, int rc);

struct ec_batch_queue;
ec_batch_queue *ec_batch_queue_create(int k, int m, size_t chunk_size,
                                      size_t max_batch, ec_batch_fn fn,
                                      void *ctx);
void ec_batch_queue_destroy(ec_batch_queue *);
int ec_batch_queue_submit(ec_batch_queue *, const unsigned char *data,
                          unsigned char *parity_out, ec_done_fn done,
                          void *done_ctx);
void ec_batch_queue_flush(ec_batch_queue *);
size_t ec_batch_queue_batches(ec_batch_queue *);
size_t ec_batch_queue_stripes(ec_batch_queue *);

}  /* extern "C" */

namespace {
struct Job {
    const unsigned char *data;
    unsigned char *parity_out;
    ec_done_fn done;
    void *done_ctx;
};
}  // namespace

struct ec_batch_queue {
    int k, m;
    size_t chunk, max_batch;
    ec_batch_fn fn;
    void *ctx;

    std::mutex mu;
    std::condition_variable cv, idle_cv;
    std::deque<Job> jobs;
    bool stop = false;
    size_t inflight = 0;
    size_t n_batches = 0, n_stripes = 0;
    std::thread worker;

    void run() {
        std::unique_lock<std::mutex> l(mu);
        std::vector<unsigned char> in_buf, out_buf;
        while (true) {
            cv.wait(l, [&] { return stop || !jobs.empty(); });
            if (stop && jobs.empty()) return;
            size_t take = jobs.size() < max_batch ? jobs.size() : max_batch;
            std::vector<Job> batch(jobs.begin(), jobs.begin() + take);
            jobs.erase(jobs.begin(), jobs.begin() + take);
            inflight += take;
            l.unlock();

            size_t dsz = (size_t)k * chunk, psz = (size_t)m * chunk;
            in_buf.resize(take * dsz);
            out_buf.resize(take * psz);
            for (size_t i = 0; i < take; i++)
                std::memcpy(&in_buf[i * dsz], batch[i].data, dsz);
            int rc = fn(ctx, in_buf.data(), out_buf.data(), take, chunk);
            for (size_t i = 0; i < take; i++) {
                if (rc == 0)
                    std::memcpy(batch[i].parity_out, &out_buf[i * psz], psz);
                if (batch[i].done) batch[i].done(batch[i].done_ctx, rc);
            }

            l.lock();
            inflight -= take;
            n_batches++;
            n_stripes += take;
            if (jobs.empty() && inflight == 0) idle_cv.notify_all();
        }
    }
};

ec_batch_queue *ec_batch_queue_create(int k, int m, size_t chunk_size,
                                      size_t max_batch, ec_batch_fn fn,
                                      void *ctx) {
    auto *q = new ec_batch_queue;
    q->k = k;
    q->m = m;
    q->chunk = chunk_size;
    q->max_batch = max_batch ? max_batch : 256;
    q->fn = fn;
    q->ctx = ctx;
    q->worker = std::thread([q] { q->run(); });
    return q;
}

void ec_batch_queue_destroy(ec_batch_queue *q) {
    {
        std::lock_guard<std::mutex> l(q->mu);
        q->stop = true;
    }
    q->cv.notify_all();
    q->worker.join();
    delete q;
}

int ec_batch_queue_submit(ec_batch_queue *q, const unsigned char *data,
                          unsigned char *parity_out, ec_done_fn done,
                          void *done_ctx) {
    {
        std::lock_guard<std::mutex> l(q->mu);
        if (q->stop) return -1;
        q->jobs.push_back(Job{data, parity_out, done, done_ctx});
    }
    q->cv.notify_one();
    return 0;
}

void ec_batch_queue_flush(ec_batch_queue *q) {
    std::unique_lock<std::mutex> l(q->mu);
    q->idle_cv.wait(l, [&] { return q->jobs.empty() && q->inflight == 0; });
}

size_t ec_batch_queue_batches(ec_batch_queue *q) {
    std::lock_guard<std::mutex> l(q->mu);
    return q->n_batches;
}

size_t ec_batch_queue_stripes(ec_batch_queue *q) {
    std::lock_guard<std::mutex> l(q->mu);
    return q->n_stripes;
}
