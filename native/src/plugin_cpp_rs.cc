/* libec_cpp_rs.so: native Reed-Solomon GF(2^8) codec plugin.
 *
 * The framework's CPU-side sibling of the reference's isa/jerasure plugins
 * (reference: src/erasure-code/isa/ErasureCodeIsa.cc — technique selection
 * :36-38, decode-table LRU keyed by erasure signature :227-304, parameter
 * envelope :323-364; src/erasure-code/jerasure/ErasureCodeJerasure.cc —
 * reed_sol_van defaults :81).  Serves as the synchronous fallback path of
 * the TPU plugin (single-stripe latency) and as the registry's
 * proof-of-contract plugin.  Profile keys: k, m, technique
 * (reed_sol_van | cauchy | vandermonde_isa).
 */
#include "../include/ec_abi.h"
#include "gf8.h"

#include <algorithm>
#include <cerrno>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <list>
#include <map>
#include <mutex>
#include <string>
#include <vector>

namespace {

constexpr unsigned SIMD_ALIGN = 32;   /* ErasureCode.cc:42 */
constexpr int DECODE_LRU_CAP = 2516;  /* ErasureCodeIsaTableCache.h:46-48 */

struct Codec;
struct CachedDecode {
    gf8::Matrix rows;
    std::vector<int> src;
};

struct Codec {
    int k = 0, m = 0;
    gf8::Matrix parity;               /* [m, k] */
    /* decode-table LRU keyed by erasure signature, the reference's
     * ErasureCodeIsaTableCache scheme (ErasureCodeIsa.cc:227-304) */
    std::mutex lru_mutex;
    std::map<std::string, std::pair<CachedDecode,
        std::list<std::string>::iterator>> cache;
    std::list<std::string> lru;
};

ec_codec *rs_create(const char *const *keys, const char *const *vals,
                    int nprof, char *errbuf, int errlen) {
    gf8::init_tables();
    int k = 7, m = 3;                 /* reed_sol_van defaults (:81) */
    std::string technique = "reed_sol_van";
    for (int i = 0; i < nprof; i++) {
        if (!std::strcmp(keys[i], "k")) k = std::atoi(vals[i]);
        else if (!std::strcmp(keys[i], "m")) m = std::atoi(vals[i]);
        else if (!std::strcmp(keys[i], "technique")) technique = vals[i];
    }
    if (k < 1 || m < 1 || k + m > 256) {
        if (errbuf) std::snprintf(errbuf, errlen,
                                  "bad k=%d m=%d (k+m must be <= 256)", k, m);
        return nullptr;
    }
    auto *c = new Codec;
    c->k = k;
    c->m = m;
    if (technique == "cauchy")
        c->parity = gf8::cauchy1(k, m);
    else if (technique == "vandermonde_isa")
        c->parity = gf8::rs_vandermonde_isa(k, m);
    else if (technique == "reed_sol_van")
        c->parity = gf8::rs_vandermonde_jerasure(k, m);
    else {
        if (errbuf) std::snprintf(errbuf, errlen, "unknown technique %s",
                                  technique.c_str());
        delete c;
        return nullptr;
    }
    if (c->parity.empty()) {
        if (errbuf) std::snprintf(errbuf, errlen,
                                  "degenerate matrix for k=%d m=%d", k, m);
        delete c;
        return nullptr;
    }
    return (ec_codec *)c;
}

void rs_destroy(ec_codec *cc) { delete (Codec *)cc; }

int rs_k(const ec_codec *cc) { return ((const Codec *)cc)->k; }
int rs_n(const ec_codec *cc) {
    const Codec *c = (const Codec *)cc;
    return c->k + c->m;
}

unsigned rs_chunk_size(const ec_codec *cc, unsigned object_size) {
    /* ceil(object_size / k) padded to SIMD_ALIGN per chunk
     * (ErasureCode::get_chunk_size + encode_prepare, ErasureCode.cc:151) */
    const Codec *c = (const Codec *)cc;
    unsigned per = (object_size + c->k - 1) / c->k;
    return (per + SIMD_ALIGN - 1) / SIMD_ALIGN * SIMD_ALIGN;
}

int rs_encode(ec_codec *cc, const unsigned char *data, unsigned char *parity,
              size_t chunk_size) {
    Codec *c = (Codec *)cc;
    gf8::apply_matrix(c->parity.data(), c->m, c->k, data, parity, chunk_size);
    return 0;
}

bool lookup_decode(Codec *c, const std::vector<int> &erasures,
                   const std::vector<int> &available, CachedDecode &out) {
    /* canonical signature like the reference's "+0+1-3..." key (:169-189);
     * inputs must be pre-sorted by the caller so equivalent requests share
     * one entry.  `out` is a copy: the cached entry may be evicted by a
     * concurrent decode the moment the lock drops. */
    std::string sig;
    for (int e : erasures) sig += "-" + std::to_string(e);
    sig += "|";
    for (int a : available) sig += "+" + std::to_string(a);

    std::lock_guard<std::mutex> l(c->lru_mutex);
    auto it = c->cache.find(sig);
    if (it != c->cache.end()) {
        c->lru.erase(it->second.second);
        c->lru.push_front(sig);
        it->second.second = c->lru.begin();
        out = it->second.first;
        return true;
    }
    CachedDecode cd;
    if (!gf8::decode_matrix(c->parity, c->k, c->m, erasures, available,
                            cd.rows, cd.src))
        return false;
    out = cd;
    if ((int)c->cache.size() >= DECODE_LRU_CAP) {
        c->cache.erase(c->lru.back());
        c->lru.pop_back();
    }
    c->lru.push_front(sig);
    c->cache.emplace(sig, std::make_pair(std::move(cd), c->lru.begin()));
    return true;
}

int rs_decode(ec_codec *cc, unsigned char **chunks, size_t chunk_size,
              const int *erasures, int n_erasures) {
    Codec *c = (Codec *)cc;
    int n = c->k + c->m;
    std::vector<int> er(erasures, erasures + n_erasures);
    std::vector<int> avail;
    std::vector<char> is_er(n, 0);
    for (int e : er) {
        if (e < 0 || e >= n) return -EINVAL;
        is_er[e] = 1;
    }
    for (int i = 0; i < n; i++)
        if (!is_er[i] && chunks[i]) avail.push_back(i);
    std::sort(er.begin(), er.end());       /* canonical cache key + row order */
    CachedDecode cd;
    if (!lookup_decode(c, er, avail, cd)) return -EIO;

    std::vector<const uint8_t *> in;
    for (int s : cd.src) in.push_back(chunks[s]);
    std::vector<uint8_t *> out;
    for (int e : er) out.push_back(chunks[e]);
    gf8::apply_matrix_ptrs(cd.rows.data(), (int)er.size(), c->k,
                           in.data(), out.data(), chunk_size);
    return 0;
}

int rs_minimum(ec_codec *cc, const int *erasures, int n_erasures,
               const int *available, int n_available, int *want_out,
               int cap) {
    /* "want if all available, else first k available"
     * (ErasureCode::_minimum_to_decode, ErasureCode.cc:103-120) */
    Codec *c = (Codec *)cc;
    int n = c->k + c->m;
    std::vector<char> is_er(n, 0);
    for (int i = 0; i < n_erasures; i++) {
        if (erasures[i] < 0 || erasures[i] >= n) return -EINVAL;
        is_er[erasures[i]] = 1;
    }
    int got = 0;
    for (int i = 0; i < n_available && got < c->k; i++) {
        if (available[i] < 0 || available[i] >= n) return -EINVAL;
        if (is_er[available[i]]) continue;
        if (got < cap) want_out[got] = available[i];
        got++;
    }
    return got >= c->k ? got : -EIO;
}

const ec_codec_ops RS_OPS = {
    rs_create, rs_destroy, rs_k, rs_n, rs_chunk_size,
    rs_encode, rs_decode, rs_minimum,
};

}  // namespace

extern "C" const char *__erasure_code_version(void) { return EC_ABI_VERSION; }

extern "C" int __erasure_code_init(const char *plugin_name,
                                   const char *directory) {
    (void)directory;
    return ec_registry_add(plugin_name, &RS_OPS);
}
