/* Plugin registry: name -> ops map + dlopen loader.
 *
 * Mirror of the reference's ErasureCodePluginRegistry
 * (reference: src/erasure-code/ErasureCodePlugin.cc): process-wide
 * singleton (:37), load() dlopens "libec_<name>.so" with RTLD_NOW (:126-137),
 * rejects version mismatches against the host's version (:139-150), calls
 * the C entry point __erasure_code_init(name, directory) which must
 * self-register (:151-173), and preload() walks a comma-separated list the
 * way global_init does with osd_erasure_code_plugins (:186-202).
 */
#include "../include/ec_abi.h"

#include <dlfcn.h>
#include <cerrno>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <string>

namespace {
std::mutex g_mutex;                      /* the registry's Mutex (:37) */
std::map<std::string, const ec_codec_ops *> &plugins() {
    static std::map<std::string, const ec_codec_ops *> m;
    return m;
}
void seterr(char *errbuf, int errlen, const char *fmt, const char *a,
            const char *b) {
    if (errbuf && errlen > 0) std::snprintf(errbuf, errlen, fmt, a, b);
}
}  // namespace

extern "C" int ec_registry_add(const char *name, const ec_codec_ops *ops) {
    /* no lock: called from __erasure_code_init which runs under the load
     * lock, matching the reference's add() contract (:59-69) */
    if (!name || !ops) return -EINVAL;
    auto &m = plugins();
    if (m.count(name)) return -EEXIST;
    m[name] = ops;
    return 0;
}

extern "C" const ec_codec_ops *ec_registry_get(const char *name) {
    std::lock_guard<std::mutex> l(g_mutex);
    auto &m = plugins();
    auto it = m.find(name);
    return it == m.end() ? nullptr : it->second;
}

extern "C" int ec_registry_count(void) {
    std::lock_guard<std::mutex> l(g_mutex);
    return (int)plugins().size();
}

extern "C" int ec_registry_load(const char *name, const char *directory,
                                char *errbuf, int errlen) {
    std::lock_guard<std::mutex> l(g_mutex);
    if (plugins().count(name)) return 0;         /* already registered */

    std::string fname = std::string(directory && *directory ? directory : ".")
        + "/" + EC_PLUGIN_PREFIX + name + EC_PLUGIN_SUFFIX;
    void *library = dlopen(fname.c_str(), RTLD_NOW);   /* (:134) */
    if (!library) {
        seterr(errbuf, errlen, "load dlopen(%s): %s", fname.c_str(),
               dlerror());
        return -EIO;
    }

    using version_fn = const char *(*)(void);
    version_fn vf = (version_fn)dlsym(library, "__erasure_code_version");
    if (!vf) {                                   /* (:139-143) */
        seterr(errbuf, errlen, "%s lacks __erasure_code_version%s",
               fname.c_str(), "");
        dlclose(library);
        return -ENOENT;
    }
    const char *ver = vf();
    if (std::strcmp(ver, EC_ABI_VERSION) != 0) { /* (:144-150) */
        seterr(errbuf, errlen,
               "plugin version %s does not match host %s", ver,
               EC_ABI_VERSION);
        dlclose(library);
        return -ENXIO;
    }

    using init_fn = int (*)(const char *, const char *);
    init_fn init = (init_fn)dlsym(library, "__erasure_code_init");
    if (!init) {                                 /* (:163-168) */
        seterr(errbuf, errlen, "%s lacks __erasure_code_init%s",
               fname.c_str(), "");
        dlclose(library);
        return -ENOENT;
    }
    int r = init(name, directory ? directory : "");
    if (r != 0) {                                /* (:151-162) */
        seterr(errbuf, errlen, "init of %s failed%s", name, "");
        /* an init that self-registered and THEN failed must not leave a
         * dangling ops pointer into the soon-unmapped library */
        plugins().erase(name);
        dlclose(library);
        return r;
    }
    if (!plugins().count(name)) {                /* init must self-register */
        seterr(errbuf, errlen, "%s did not register plugin %s",
               fname.c_str(), name);
        dlclose(library);
        return -EBADF;
    }
    /* library intentionally stays open for the process lifetime, like the
     * reference (registry keeps the handle, never dlcloses on success) */
    return 0;
}

extern "C" int ec_registry_preload(const char *names_csv,
                                   const char *directory,
                                   char *errbuf, int errlen) {
    if (!names_csv) return 0;
    std::string csv(names_csv);
    size_t pos = 0;
    while (pos < csv.size()) {
        size_t comma = csv.find(',', pos);
        std::string name = csv.substr(
            pos, comma == std::string::npos ? std::string::npos : comma - pos);
        if (!name.empty()) {
            int r = ec_registry_load(name.c_str(), directory, errbuf, errlen);
            if (r && r != -EEXIST) return r;
        }
        if (comma == std::string::npos) break;
        pos = comma + 1;
    }
    return 0;
}
