/* See gf8.h.  Table generation mirrors ceph_tpu/gf/tables.py exactly. */
#include "gf8.h"

#include <algorithm>
#include <cstring>
#include <mutex>

namespace gf8 {

uint8_t EXP[512];
uint8_t LOG[256];
uint8_t MUL[256][256];

void init_tables() {
    /* thread-safe once-init: concurrent rs_create calls arrive with the
     * GIL released (ctypes), so a plain bool guard would race */
    static std::once_flag once;
    std::call_once(once, [] {
        int x = 1;
        for (int i = 0; i < 255; i++) {
            EXP[i] = (uint8_t)x;
            LOG[x] = (uint8_t)i;
            x <<= 1;
            if (x & 0x100) x ^= POLY;
        }
        std::memcpy(EXP + 255, EXP, 255);
        LOG[0] = 0;
        for (int a = 0; a < 256; a++) {
            MUL[0][a] = MUL[a][0] = 0;
            for (int b = 1; b <= a; b++)
                MUL[a][b] = MUL[b][a] = (a == 0) ? 0 : EXP[LOG[a] + LOG[b]];
        }
    });
}

uint8_t inv(uint8_t a) {
    if (a == 0) return 0;             /* callers must not invert 0 */
    return EXP[255 - LOG[a]];
}

uint8_t gfpow(uint8_t a, int n) {
    if (n == 0) return 1;
    if (a == 0) return 0;
    return EXP[(LOG[a] * (long)n) % 255];
}

Matrix rs_vandermonde_isa(int k, int m) {
    /* row r, col j = (2^r)^j (ErasureCodeIsa.cc:384 gf_gen_rs_matrix) */
    Matrix a((size_t)m * k);
    uint8_t gen = 1;
    for (int r = 0; r < m; r++) {
        uint8_t p = 1;
        for (int j = 0; j < k; j++) {
            a[(size_t)r * k + j] = p;
            p = mul(p, gen);
        }
        gen = mul(gen, 2);
    }
    return a;
}

Matrix cauchy1(int k, int m) {
    /* row i, col j = inv((i+k) ^ j) (gf_gen_cauchy1_matrix) */
    Matrix a((size_t)m * k);
    for (int i = 0; i < m; i++)
        for (int j = 0; j < k; j++)
            a[(size_t)i * k + j] = inv((uint8_t)((i + k) ^ j));
    return a;
}

bool invert(const Matrix &in, Matrix &out, int n) {
    std::vector<uint8_t> aug((size_t)n * 2 * n, 0);
    for (int r = 0; r < n; r++) {
        std::memcpy(&aug[(size_t)r * 2 * n], &in[(size_t)r * n], n);
        aug[(size_t)r * 2 * n + n + r] = 1;
    }
    for (int col = 0; col < n; col++) {
        int piv = col;
        while (piv < n && aug[(size_t)piv * 2 * n + col] == 0) piv++;
        if (piv == n) return false;
        if (piv != col)
            for (int j = 0; j < 2 * n; j++)
                std::swap(aug[(size_t)col * 2 * n + j],
                          aug[(size_t)piv * 2 * n + j]);
        uint8_t v = aug[(size_t)col * 2 * n + col];
        if (v != 1) {
            uint8_t iv = inv(v);
            for (int j = 0; j < 2 * n; j++)
                aug[(size_t)col * 2 * n + j] =
                    mul(aug[(size_t)col * 2 * n + j], iv);
        }
        for (int r = 0; r < n; r++) {
            uint8_t t = aug[(size_t)r * 2 * n + col];
            if (r != col && t != 0)
                for (int j = 0; j < 2 * n; j++)
                    aug[(size_t)r * 2 * n + j] ^=
                        mul(aug[(size_t)col * 2 * n + j], t);
        }
    }
    out.assign((size_t)n * n, 0);
    for (int r = 0; r < n; r++)
        std::memcpy(&out[(size_t)r * n], &aug[(size_t)r * 2 * n + n], n);
    return true;
}

Matrix matmul(const Matrix &a, int ar, int ac, const Matrix &b, int bc) {
    Matrix out((size_t)ar * bc, 0);
    for (int i = 0; i < ar; i++)
        for (int j = 0; j < ac; j++) {
            uint8_t v = a[(size_t)i * ac + j];
            if (!v) continue;
            const uint8_t *row = MUL[v];
            for (int c = 0; c < bc; c++)
                out[(size_t)i * bc + c] ^= row[b[(size_t)j * bc + c]];
        }
    return out;
}

Matrix rs_vandermonde_jerasure(int k, int m) {
    /* systematic EXTENDED Vandermonde exactly as jerasure's
     * reed_sol_vandermonde_coding_matrix publishes it (Plank & Ding 2003
     * correction): natural rows i^j plus the extension row e_{k-1} last,
     * systematized, then every COLUMN divided by the first coding row's
     * entry so that row is all ones (matches ceph_tpu/gf/matrix.py and
     * the longhand re-derivation in tests/test_ec_external_vectors.py) */
    int rows = k + m;
    Matrix vdm((size_t)rows * k);
    for (int i = 0; i < rows - 1; i++) {
        vdm[(size_t)i * k] = 1;
        for (int j = 1; j < k; j++)
            vdm[(size_t)i * k + j] = mul(vdm[(size_t)i * k + j - 1],
                                         (uint8_t)i);
    }
    vdm[(size_t)(rows - 1) * k + (k - 1)] = 1;   /* extension row e_{k-1} */
    Matrix top((size_t)k * k);
    std::memcpy(top.data(), vdm.data(), (size_t)k * k);
    Matrix top_inv;
    if (!invert(top, top_inv, k)) return Matrix();
    Matrix bottom((size_t)m * k);
    std::memcpy(bottom.data(), &vdm[(size_t)k * k], (size_t)m * k);
    Matrix parity = matmul(bottom, m, k, top_inv, k);
    for (int j = 0; j < k; j++) {
        uint8_t c = parity[j];
        if (c == 0) return Matrix();       /* degenerate */
        if (c != 1) {
            uint8_t iv = inv(c);
            for (int r = 0; r < m; r++)
                parity[(size_t)r * k + j] = mul(parity[(size_t)r * k + j], iv);
        }
    }
    /* reed_sol.c's final step: scale coding rows 1..m-1 so the first
     * COLUMN of the parity block is all ones too */
    for (int r = 1; r < m; r++) {
        uint8_t c = parity[(size_t)r * k];
        if (c == 0) return Matrix();       /* degenerate */
        if (c != 1) {
            uint8_t iv = inv(c);
            for (int j = 0; j < k; j++)
                parity[(size_t)r * k + j] = mul(parity[(size_t)r * k + j], iv);
        }
    }
    return parity;
}

bool decode_matrix(const Matrix &parity, int k, int m,
                   const std::vector<int> &erasures,
                   const std::vector<int> &available,
                   Matrix &rows, std::vector<int> &src) {
    std::vector<char> erased(k + m, 0);
    for (int e : erasures) erased[e] = 1;
    src.clear();
    for (int a : available)
        if (!erased[a] && (int)src.size() < k) src.push_back(a);
    if ((int)src.size() < k) return false;

    /* generator rows of the survivors */
    Matrix sub((size_t)k * k, 0);
    for (int r = 0; r < k; r++) {
        int id = src[r];
        if (id < k)
            sub[(size_t)r * k + id] = 1;
        else
            std::memcpy(&sub[(size_t)r * k], &parity[(size_t)(id - k) * k], k);
    }
    Matrix invm;
    if (!invert(sub, invm, k)) return false;

    rows.assign(erasures.size() * (size_t)k, 0);
    size_t out_r = 0;
    std::vector<int> sorted_erasures(erasures.begin(), erasures.end());
    std::sort(sorted_erasures.begin(), sorted_erasures.end());
    for (int e : sorted_erasures) {
        if (e < k) {
            std::memcpy(&rows[out_r * k], &invm[(size_t)e * k], k);
        } else {
            Matrix prow((size_t)k);
            std::memcpy(prow.data(), &parity[(size_t)(e - k) * k], k);
            Matrix res = matmul(prow, 1, k, invm, k);
            std::memcpy(&rows[out_r * k], res.data(), k);
        }
        out_r++;
    }
    return true;
}

void apply_matrix(const uint8_t *coef, int nout, int nin,
                  const uint8_t *in, uint8_t *out, size_t chunk_size) {
    if (simd_level() > 0 && nout <= 32 && chunk_size >= 64) {
        const uint8_t *inp[32];
        uint8_t *outp[32];
        for (int j = 0; j < nin && j < 32; j++)
            inp[j] = in + (size_t)j * chunk_size;
        for (int r = 0; r < nout; r++)
            outp[r] = out + (size_t)r * chunk_size;
        if (nin <= 32 &&
            simd_apply_matrix_ptrs(coef, nout, nin, inp, outp, chunk_size))
            return;
    }
    for (int r = 0; r < nout; r++) {
        uint8_t *dst = out + (size_t)r * chunk_size;
        std::memset(dst, 0, chunk_size);
        for (int j = 0; j < nin; j++) {
            uint8_t c = coef[(size_t)r * nin + j];
            if (!c) continue;
            const uint8_t *row = MUL[c];
            const uint8_t *srcp = in + (size_t)j * chunk_size;
            if (c == 1) {
                for (size_t i = 0; i < chunk_size; i++) dst[i] ^= srcp[i];
            } else {
                for (size_t i = 0; i < chunk_size; i++) dst[i] ^= row[srcp[i]];
            }
        }
    }
}

void apply_matrix_ptrs(const uint8_t *coef, int nout, int nin,
                       const uint8_t *const *in, uint8_t *const *out,
                       size_t chunk_size) {
    if (chunk_size >= 64 &&
        simd_apply_matrix_ptrs(coef, nout, nin, in, out, chunk_size))
        return;
    for (int r = 0; r < nout; r++) {
        uint8_t *dst = out[r];
        std::memset(dst, 0, chunk_size);
        for (int j = 0; j < nin; j++) {
            uint8_t c = coef[(size_t)r * nin + j];
            if (!c) continue;
            const uint8_t *row = MUL[c];
            const uint8_t *srcp = in[j];
            if (c == 1) {
                for (size_t i = 0; i < chunk_size; i++) dst[i] ^= srcp[i];
            } else {
                for (size_t i = 0; i < chunk_size; i++) dst[i] ^= row[srcp[i]];
            }
        }
    }
}

}  // namespace gf8
