/* Deliberately-broken plugins for registry failure-path tests.
 *
 * Mirror of the reference's test plugin family (reference:
 * src/test/erasure-code/ErasureCodePlugin{FailToInitialize,FailToRegister,
 * MissingEntryPoint,MissingVersion}.cc): each TEST_PLUGIN_* macro selects
 * one failure mode at compile time; the Makefile builds one .so per mode.
 */
#include "../include/ec_abi.h"

#if defined(TEST_PLUGIN_WRONG_VERSION)
extern "C" const char *__erasure_code_version(void) { return "bogus-0"; }
extern "C" int __erasure_code_init(const char *, const char *) { return 0; }

#elif defined(TEST_PLUGIN_FAIL_INIT)
extern "C" const char *__erasure_code_version(void) { return EC_ABI_VERSION; }
extern "C" int __erasure_code_init(const char *, const char *) { return -5; }

#elif defined(TEST_PLUGIN_FAIL_REGISTER)
/* init "succeeds" but never calls ec_registry_add */
extern "C" const char *__erasure_code_version(void) { return EC_ABI_VERSION; }
extern "C" int __erasure_code_init(const char *, const char *) { return 0; }

#elif defined(TEST_PLUGIN_MISSING_ENTRY)
/* version only; no __erasure_code_init symbol */
extern "C" const char *__erasure_code_version(void) { return EC_ABI_VERSION; }

#else
#error "define one TEST_PLUGIN_* mode"
#endif
