/* GF(2^8) arithmetic, RS matrices, and region encode/decode kernels.
 *
 * Native analog of ceph_tpu/gf (poly 0x11D, the jerasure w=8 field) —
 * bit-identical tables and matrix constructions so the C++ fallback path
 * and the JAX device path produce the same chunks.  Matrix semantics cite
 * the reference: gf_gen_rs_matrix / gf_gen_cauchy1_matrix usage at
 * src/erasure-code/isa/ErasureCodeIsa.cc:384-387, decode-matrix
 * construction at :227-307.
 */
#ifndef CEPH_TPU_GF8_H
#define CEPH_TPU_GF8_H

#include <cstddef>
#include <cstdint>
#include <vector>

namespace gf8 {

constexpr uint16_t POLY = 0x11D;

extern uint8_t EXP[512];
extern uint8_t LOG[256];      /* LOG[0] undefined; callers special-case 0 */
extern uint8_t MUL[256][256];

void init_tables();           /* idempotent */

inline uint8_t mul(uint8_t a, uint8_t b) { return MUL[a][b]; }
uint8_t inv(uint8_t a);
uint8_t gfpow(uint8_t a, int n);

using Matrix = std::vector<uint8_t>;  /* row-major */

/* parity matrices [m, k] */
Matrix rs_vandermonde_isa(int k, int m);
Matrix cauchy1(int k, int m);
Matrix rs_vandermonde_jerasure(int k, int m);

/* [n, n] Gauss-Jordan inverse; returns false when singular */
bool invert(const Matrix &in, Matrix &out, int n);
/* [a_r, a_c] x [a_c, b_c] */
Matrix matmul(const Matrix &a, int ar, int ac, const Matrix &b, int bc);

/* decode matrix for erased chunk ids given the parity matrix:
 * returns rows [n_erased, k] and fills src with the k surviving chunk ids
 * used as inputs (first k survivors in ascending order,
 * ErasureCodeIsa.cc:227-307 semantics) */
bool decode_matrix(const Matrix &parity, int k, int m,
                   const std::vector<int> &erasures,
                   const std::vector<int> &available,
                   Matrix &rows, std::vector<int> &src);

/* region ops: out[r] ^= sum_j coef[r,j] * in[j] over chunk_size bytes.
 * in = nin contiguous chunks, out = nout contiguous chunks. */
void apply_matrix(const uint8_t *coef, int nout, int nin,
                  const uint8_t *in, uint8_t *out, size_t chunk_size);
/* gather variant: input chunks via pointer array */
void apply_matrix_ptrs(const uint8_t *coef, int nout, int nin,
                       const uint8_t *const *in, uint8_t *const *out,
                       size_t chunk_size);

/* SIMD acceleration (gf8_simd.cc): 0 = scalar only, 1 = AVX2 pshufb,
 * 2 = GFNI+AVX2 affine, 3 = GFNI+AVX-512 affine.  apply_matrix*
 * dispatch to the best verified level automatically. */
int simd_level();
bool simd_apply_matrix_ptrs(const uint8_t *coef, int nout, int nin,
                            const uint8_t *const *in, uint8_t *const *out,
                            size_t chunk_size);

}  // namespace gf8

#endif
