/* C ABI for erasure-code plugins (the framework's native plugin contract).
 *
 * Mirror of the reference's plugin interface surface
 * (reference: src/erasure-code/ErasureCodeInterface.h:170-462 methods;
 * src/erasure-code/ErasureCodePlugin.{h,cc} registry + dlopen contract:
 * entry points __erasure_code_init/__erasure_code_version at
 * ErasureCodePlugin.cc:24-34, version check :144, "libec_<name>.so" prefix
 * :28) reshaped as a C vtable so codecs cross the C/Python boundary without
 * C++ name mangling: Python binds via ctypes, the JAX sidecar registers a
 * batch callback (see ec_batch.h).
 */
#ifndef CEPH_TPU_EC_ABI_H
#define CEPH_TPU_EC_ABI_H

#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

/* checked against each plugin's __erasure_code_version(), the analog of
 * the CEPH_GIT_NICE_VER comparison (ErasureCodePlugin.cc:139-150) */
#define EC_ABI_VERSION "ceph-tpu-ec-1"

/* dlopen name pattern (ErasureCodePlugin.cc:28) */
#define EC_PLUGIN_PREFIX "libec_"
#define EC_PLUGIN_SUFFIX ".so"

typedef struct ec_codec ec_codec; /* opaque per-instance state */

typedef struct ec_codec_ops {
    /* init(profile) -> instance; profile is parallel key/value arrays
     * (ErasureCodeProfile is map<string,string>, Interface.h:155).
     * Returns NULL and fills errbuf on bad profile. */
    ec_codec *(*create)(const char *const *prof_keys,
                        const char *const *prof_vals, int nprof,
                        char *errbuf, int errlen);
    void (*destroy)(ec_codec *);

    int (*get_data_chunk_count)(const ec_codec *);   /* k  (:237) */
    int (*get_chunk_count)(const ec_codec *);        /* k+m (:227) */
    /* chunk size for an object size, padded/aligned the way
     * ErasureCode::get_chunk_size + SIMD_ALIGN do (ErasureCode.cc:42,151) */
    unsigned (*get_chunk_size)(const ec_codec *, unsigned object_size);

    /* encode_chunks (:370): data = k contiguous chunks of chunk_size bytes,
     * parity out = m contiguous chunks.  Returns 0 or -errno. */
    int (*encode)(ec_codec *, const unsigned char *data,
                  unsigned char *parity, size_t chunk_size);

    /* decode_chunks (:411): chunks[i] for i in [0, k+m) point at
     * chunk_size-byte buffers; entries listed in erasures[] are outputs
     * (reconstructed in place), the rest are inputs.  Returns 0 or -errno. */
    int (*decode)(ec_codec *, unsigned char **chunks, size_t chunk_size,
                  const int *erasures, int n_erasures);

    /* minimum_to_decode (:297): fills want_out (cap n) with the chunk ids
     * to read for recovering `erasures` given `available`; returns count
     * or -EIO when unrecoverable. */
    int (*minimum_to_decode)(ec_codec *, const int *erasures, int n_erasures,
                             const int *available, int n_available,
                             int *want_out, int cap);
} ec_codec_ops;

/* ---- registry (exported by libec_registry.so) ------------------------- */

/* self-registration, called from a plugin's __erasure_code_init */
int ec_registry_add(const char *name, const ec_codec_ops *ops);
const ec_codec_ops *ec_registry_get(const char *name);
/* dlopen(directory/libec_<name>.so), verify version, run init
 * (ErasureCodePlugin.cc:126-184).  0 on success, -errno + errbuf else. */
int ec_registry_load(const char *name, const char *directory,
                     char *errbuf, int errlen);
/* comma-separated preload list (global_init preload_erasure_code,
 * option osd_erasure_code_plugins) */
int ec_registry_preload(const char *names_csv, const char *directory,
                        char *errbuf, int errlen);
int ec_registry_count(void);

/* ---- plugin entry points (each libec_<name>.so exports these) --------- */
/* const char *__erasure_code_version(void);
 * int __erasure_code_init(const char *plugin_name, const char *directory);
 */

#ifdef __cplusplus
}
#endif
#endif /* CEPH_TPU_EC_ABI_H */
